"""The ``tts serve`` daemon: localhost HTTP/JSON + per-job SSE.

Zero-dependency by the same rule as ``obs/live.py`` (stdlib
``http.server`` only, bound to 127.0.0.1 — an operator-side service, not
an internet surface). The HTTP threads only touch the registry, the
scheduler queue, and the pool's bookkeeping; jax lives entirely in the
scheduler workers.

API (all JSON):

  * ``POST /submit``             — body: a job spec (serve/jobs.py).
    201 -> ``{id, class, warm, position}``; 400 invalid spec; 503 when
    the queue is at ``--max-queue`` (admission control back-pressure).
  * ``GET  /jobs``               — every job record, id-ordered.
  * ``GET  /job/<id>``           — one job record (404 unknown).
  * ``GET  /job/<id>/result``    — the result record; 409 until the job
    reaches a terminal state (a blocking client polls or streams).
  * ``POST /job/<id>/cancel``    — cancel queued now / running at the
    next dispatch boundary; 409 when already finished.
  * ``GET  /job/<id>/checkpoint``— the job's checkpoint as raw npz bytes
    (409 when the job has none) — with ``resume_ckpt_b64`` on ``/submit``
    this is the ``tts migrate`` transport: cut on daemon A, resubmit the
    spec + checkpoint on daemon B, counters stay cumulative.
  * ``GET  /job/<id>/stream``    — SSE: one frame per new snapshot from
    the job's private flight-recorder ring (incumbent, nodes/s, pool
    occupancy ...) plus ``event: incumbent`` frames — one per recorded
    quality-trajectory improvement, all flushed before the terminal
    ``event: done`` frame carrying the final job record — one connection
    is the whole job story.
  * ``GET  /classes``            — program-pool stats per shape class.
  * ``GET  /metrics``            — Prometheus text format (serve/metrics.py):
    queue depth, jobs by state/class, admission outcomes, pool occupancy,
    compile deltas, preemptions, wait/run histograms.
  * ``GET  /healthz``            — liveness + queue depth + ``uptime_s``,
    ``version`` and ``workers_alive`` (a dead worker thread must not hide
    behind a healthy-looking HTTP surface).
  * ``POST /shutdown``           — graceful drain (same path as SIGTERM).
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import urlparse

from ..obs.live import sse_begin, stream_snapshots
from . import DEFAULT_PORT, VERSION
from . import metrics as metrics_mod
from .jobs import JobRegistry, validate_spec
from .pool import ProgramPool
from .scheduler import Scheduler

#: Jobs in a terminal state (no further transitions).
FINAL_STATES = ("done", "failed", "cancelled")


def default_state_dir() -> str:
    return os.environ.get("TTS_SERVE_STATE") or os.path.join(
        os.path.expanduser("~"), ".cache", "tpu_tree_search", "serve"
    )


class ServeDaemon:
    """The daemon's spine: registry + pool + scheduler + HTTP server."""

    def __init__(self, port: int = DEFAULT_PORT, host: str = "127.0.0.1",
                 state_dir: str | None = None, workers: int = 1,
                 quantum_s: float = 5.0, max_queue: int = 64,
                 batch_slots: int | None = None,
                 ckpt_every_s: float | None = None):
        self.state_dir = state_dir or default_state_dir()
        os.makedirs(self.state_dir, exist_ok=True)
        self.registry = JobRegistry(self.state_dir)
        self.loaded = self.registry.load()
        self.pool = ProgramPool()
        self.metrics = metrics_mod.ServeMetrics()
        self.started = time.time()
        self.scheduler = Scheduler(self.registry, self.pool, workers=workers,
                                   quantum_s=quantum_s,
                                   state_dir=self.state_dir,
                                   metrics=self.metrics,
                                   batch_slots=batch_slots,
                                   ckpt_every_s=ckpt_every_s)
        self.max_queue = max_queue
        self.stop_event = threading.Event()
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.daemon = self  # handler back-reference
        self.host = host
        self.port = self._httpd.server_address[1]
        self._http_thread = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> None:
        self.scheduler.start()
        # Jobs interrupted by a previous daemon come back requeued with
        # their checkpoints: re-admit them in id order before new work.
        for job in self.registry.all():
            if job.state == "requeued":
                self.registry.transition(job, "queued")
                self.scheduler.submit(job)
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.2},
            name="tts-serve-http", daemon=True,
        )
        self._http_thread.start()

    def submit(self, spec) -> tuple[dict, int]:
        """Admission: validate -> classify -> enqueue. Returns (payload,
        http status). Runs in HTTP threads — no jax, no problem builds.

        An optional top-level ``resume_ckpt_b64`` (the ``tts migrate``
        transport) carries a checkpoint from another daemon: it is
        decoded to a per-job file and attached BEFORE the job is
        enqueued, so the first slice resumes from it — a worker can pop
        the job the instant ``scheduler.submit`` returns."""
        ckpt_b64 = None
        if isinstance(spec, dict) and "resume_ckpt_b64" in spec:
            spec = dict(spec)
            ckpt_b64 = spec.pop("resume_ckpt_b64")
            import base64
            import binascii

            try:
                ckpt_b64 = base64.b64decode(ckpt_b64, validate=True)
            except (TypeError, ValueError, binascii.Error):
                self.metrics.inc("tts_serve_admissions_total",
                                 {"outcome": "invalid"})
                return {"error": "invalid resume_ckpt_b64"}, 400
        try:
            spec = validate_spec(spec)
        except ValueError as e:
            self.metrics.inc("tts_serve_admissions_total",
                             {"outcome": "invalid"})
            return {"error": str(e)}, 400
        if self.scheduler.queue_depth() >= self.max_queue:
            self.metrics.inc("tts_serve_admissions_total",
                             {"outcome": "queue_full"})
            return {"error": f"queue full ({self.max_queue})"}, 503
        cls = self.pool.peek(spec)
        from .jobs import job_pins

        job = self.registry.create(spec, cls["class"], job_pins(spec),
                                   warm_hit=cls["warm"])
        if ckpt_b64 is not None:
            # Validity against the spec's problem is checked by the worker
            # (engine/checkpoint.py's meta validation) — a mismatched
            # checkpoint fails THIS job with a clear error, not the daemon.
            jobs_dir = os.path.join(self.state_dir, "jobs")
            os.makedirs(jobs_dir, exist_ok=True)
            path = os.path.join(jobs_dir, f"{job.id}.resume.ckpt.npz")
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(ckpt_b64)
            os.replace(tmp, path)
            self.registry.update(job, checkpoint=path)
        try:
            pos = self.scheduler.submit(job)
        except RuntimeError:
            self.registry.transition(job, "requeued")
            self.metrics.inc("tts_serve_admissions_total",
                             {"outcome": "draining"})
            return {"error": "daemon is draining"}, 503
        self.metrics.inc("tts_serve_admissions_total",
                         {"outcome": "admitted"})
        return {"id": job.id, "class": cls["class"], "warm": cls["warm"],
                "position": pos}, 201

    def health(self) -> dict:
        """The ``/healthz`` payload. ``workers_alive`` counts scheduler
        worker threads still running — the PR-10 worker wrap makes a
        per-job crash survivable, but an exhausted/killed worker thread
        would otherwise leave a daemon that admits jobs and never runs
        them; ``ok`` goes false in that state so probes (and the submit
        client's error message) surface it."""
        alive = self.scheduler.workers_alive()
        started = self.scheduler.started
        return {
            "ok": alive > 0 or not started,
            # The fleet router's keeper reads this to trigger the live
            # recovery path (migrate-off) while the HTTP surface still
            # answers, instead of waiting out the death detector.
            "draining": self.scheduler._stop_requested(),
            "queue_depth": self.scheduler.queue_depth(),
            "jobs": len(self.registry.all()),
            "uptime_s": round(max(0.0, time.time() - self.started), 3),
            "version": VERSION,
            "workers": self.scheduler.workers,
            "workers_alive": alive,
            "batch_slots": self.scheduler.batch_slots,
        }

    def shutdown(self) -> None:
        """Graceful drain; idempotent (SIGTERM and POST /shutdown share
        it). Runs the scheduler drain in the caller's thread, then wakes
        the main loop."""
        self.scheduler.drain()
        self.stop_event.set()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


class _Handler(BaseHTTPRequestHandler):
    server_version = "tts-serve/1"

    def log_message(self, fmt, *args):  # silence per-request stderr noise
        pass

    @property
    def daemon(self) -> ServeDaemon:
        return self.server.daemon

    def _json(self, payload, code: int = 200) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _body(self, limit: int = 1 << 20):
        n = int(self.headers.get("Content-Length") or 0)
        if n <= 0 or n > limit:
            return None
        try:
            return json.loads(self.rfile.read(n).decode())
        except (ValueError, UnicodeDecodeError):
            return None

    def _job(self, jid: str):
        return self.daemon.registry.get(jid)

    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler's contract
        path = urlparse(self.path).path
        try:
            if path == "/jobs":
                self._json([j.record() for j in self.daemon.registry.all()])
            elif path == "/classes":
                stats = self.daemon.pool.stats()
                batch = {b["class"]: b
                         for b in self.daemon.scheduler.batch_stats()}
                for st in stats:
                    b = batch.get(st.get("class"))
                    if b is not None:
                        st["batch_slots"] = b["slots"]
                        st["slots_occupied"] = b["occupied"]
                self._json(stats)
            elif path == "/metrics":
                body = metrics_mod.render(self.daemon).encode()
                self.send_response(200)
                self.send_header("Content-Type", metrics_mod.CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif path == "/healthz":
                self._json(self.daemon.health())
            elif path.startswith("/job/"):
                parts = path.split("/")  # ['', 'job', '<id>', ...]
                job = self._job(parts[2]) if len(parts) >= 3 else None
                if job is None:
                    self._json({"error": "unknown job"}, code=404)
                elif len(parts) == 3:
                    self._json(job.record())
                elif parts[3] == "result":
                    if job.state in FINAL_STATES:
                        self._json({"id": job.id, "state": job.state,
                                    "result": job.result,
                                    "error": job.error})
                    else:
                        self.daemon.metrics.inc("tts_serve_conflicts_total",
                                                {"endpoint": "result"})
                        self._json({"error": f"job is {job.state}",
                                    "state": job.state}, code=409)
                elif parts[3] == "checkpoint":
                    path = job.checkpoint
                    if (not path or not os.path.exists(path)) \
                            and job.state not in FINAL_STATES:
                        # Mid-slice fallback: job.checkpoint only updates
                        # at a cut, but a previous cut's file may already
                        # sit at the scheduler's well-known path — the
                        # fleet router's periodic pulls read it from here
                        # while the job keeps running.
                        cand = self.daemon.scheduler._checkpoint_path(job)
                        if os.path.exists(cand):
                            path = cand
                    if not path or not os.path.exists(path):
                        self.daemon.metrics.inc(
                            "tts_serve_conflicts_total",
                            {"endpoint": "checkpoint"})
                        self._json({"error": "job has no checkpoint",
                                    "state": job.state}, code=409)
                    else:
                        with open(path, "rb") as f:
                            body = f.read()
                        # Checkpoint payloads are npz (already deflated),
                        # but the header/meta rows and the base64 hop on
                        # resubmit still shave real bytes under gzip —
                        # negotiated, so plain curl keeps working.
                        accept = self.headers.get("Accept-Encoding", "")
                        gzipped = "gzip" in accept.lower()
                        if gzipped:
                            import gzip as _gzip

                            body = _gzip.compress(body, compresslevel=6)
                        self.send_response(200)
                        self.send_header("Content-Type",
                                         "application/octet-stream")
                        if gzipped:
                            self.send_header("Content-Encoding", "gzip")
                        self.send_header("Content-Length", str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                elif parts[3] == "stream":
                    self._stream_job(job)
                else:
                    self._json({"error": "unknown path"}, code=404)
            else:
                self._json({"error": "unknown path"}, code=404)
        except (BrokenPipeError, ConnectionResetError):
            pass  # client went away; nothing to clean up

    def do_POST(self):  # noqa: N802
        path = urlparse(self.path).path
        try:
            if path == "/submit":
                # Larger cap than the default: a migrated submit carries a
                # base64 checkpoint (frontier rows) in resume_ckpt_b64.
                body = self._body(limit=64 << 20)
                if body is None:
                    self._json({"error": "invalid JSON body"}, code=400)
                    return
                payload, code = self.daemon.submit(body)
                self._json(payload, code=code)
            elif path == "/shutdown":
                self._json({"ok": True, "draining": True})
                # Drain AFTER replying (it blocks until workers go idle).
                threading.Thread(target=self.daemon.shutdown,
                                 name="tts-serve-drain", daemon=True).start()
            elif path.startswith("/job/") and path.endswith("/cancel"):
                jid = path.split("/")[2]
                job = self._job(jid)
                if job is None:
                    self._json({"error": "unknown job"}, code=404)
                elif self.daemon.scheduler.cancel(job):
                    self._json({"id": job.id, "state": job.state,
                                "cancelling": True})
                else:
                    self.daemon.metrics.inc("tts_serve_conflicts_total",
                                            {"endpoint": "cancel"})
                    self._json({"error": f"job already {job.state}"},
                               code=409)
            else:
                self._json({"error": "unknown path"}, code=404)
        except (BrokenPipeError, ConnectionResetError):
            pass

    def _stream_job(self, job) -> None:
        """Per-job SSE: frames from the job's private recorder ring until
        the job finishes, then the final record as ``event: done``.
        Interleaved ``event: incumbent`` frames carry the job's quality
        trajectory (obs/quality.py) as it improves; the stream layer
        drains them once more before the ``done`` frame, so every
        incumbent recorded during the run reaches the client before the
        stream closes."""
        daemon = self.daemon
        sent = 0  # incumbent points already on this connection

        def latest():
            rec = job.recorder
            return rec.latest() if rec is not None else None

        def incumbents():
            nonlocal sent
            q = job.quality
            if q is None:
                return []
            pts = q.points()
            out = []
            while sent < len(pts):
                p = pts[sent]
                sent += 1
                # 1-based monotone index: clients dedupe reconnects by it.
                out.append(("incumbent", {**p, "n": sent, "job": job.id}))
            return out

        def stop():
            return (job.state in FINAL_STATES
                    or daemon.stop_event.is_set()
                    or getattr(self.server, "closing", False))

        sse_begin(self, comment=f"tts job stream {job.id}")
        stream_snapshots(
            self, latest, stop_fn=stop, events_fn=incumbents,
            final_fn=lambda: job.record() if job.state in FINAL_STATES
            else None,
        )


def serve_main(port: int = DEFAULT_PORT, host: str = "127.0.0.1",
               state_dir: str | None = None, workers: int = 1,
               quantum_s: float = 5.0, max_queue: int = 64,
               warm: str | None = None,
               batch_slots: int | None = None,
               ckpt_every_s: float | None = None,
               router: str | None = None) -> int:
    """The ``tts serve`` entry point: start, optionally pre-warm the pool,
    then wait for SIGTERM/SIGINT (or POST /shutdown) and drain.

    ``--router URL`` self-registers this daemon with a fleet router
    (fleet/router.py) once the HTTP surface is up; registration failure
    is reported, not fatal — the daemon serves standalone and the router
    can still be pointed at it later via POST /register.

    Signal composition: the daemon's handler is installed FIRST, so a
    later ``flightrec.install()`` (TTS_FLIGHTREC=1 operators) dumps its
    post-mortem and then chains to us — one SIGTERM yields both the
    flight-record dump and a clean drain."""
    daemon = ServeDaemon(port=port, host=host, state_dir=state_dir,
                         workers=workers, quantum_s=quantum_s,
                         max_queue=max_queue, batch_slots=batch_slots,
                         ckpt_every_s=ckpt_every_s)

    def _on_signal(signum, frame):
        # Handler context: just set the flag; the main loop drains.
        daemon.stop_event.set()

    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, _on_signal)
    from ..obs import flightrec

    if flightrec.enabled():
        flightrec.recorder().install()  # chains SIGTERM to _on_signal
    daemon.start()
    print(f"Serving on {daemon.url} (v{VERSION}, "
          f"state: {daemon.state_dir}, "
          f"workers: {daemon.scheduler.workers}, "
          f"quantum: {daemon.scheduler.quantum_s:g}s, "
          f"batch-slots: {daemon.scheduler.batch_slots}"
          + (f", reloaded {daemon.loaded} job record(s)" if daemon.loaded
             else "") + ")", flush=True)
    if router:
        from .client import _post, base_url

        try:
            code, resp = _post(base_url(router=router) + "/register",
                               {"url": daemon.url}, timeout=5.0,
                               retry_s=5.0)
            print(f"Registered with fleet router {router} "
                  f"({resp.get('daemons', '?')} daemon(s) in fleet)"
                  if code == 200 else
                  f"Fleet registration rejected ({code}): {resp}",
                  flush=True)
        except (OSError, ValueError) as e:
            print(f"Fleet registration with {router} failed ({e}); "
                  "serving standalone.", flush=True)
    if warm is not None:
        from .warmup import warm_pool

        for line in warm_pool(daemon, warm):
            print(line, flush=True)
    try:
        while not daemon.stop_event.wait(0.5):
            pass
    except KeyboardInterrupt:
        pass
    print("Draining: cutting running jobs at the next dispatch boundary "
          "(checkpointed), requeueing pending work...", flush=True)
    daemon.scheduler.drain()
    daemon.close()
    n_requeued = sum(
        1 for j in daemon.registry.all() if j.state == "requeued"
    )
    print(f"Drained ({n_requeued} job(s) requeued for the next daemon).",
          flush=True)
    return 0


def wait_ready(url: str, timeout_s: float = 30.0) -> dict | None:
    """Poll ``/healthz`` until the daemon answers; returns the health
    payload (version, uptime_s, workers_alive ...) so callers can report
    WHICH daemon answered — or a degraded one — not just that a socket
    opened. ``None`` on timeout."""
    from urllib.request import urlopen

    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            with urlopen(url + "/healthz", timeout=2.0) as resp:  # noqa: S310
                return json.loads(resp.read().decode())
        except (OSError, ValueError):
            time.sleep(0.1)
    return None


def wait_port(url: str, timeout_s: float = 30.0) -> bool:
    """Boolean convenience over :func:`wait_ready` (client/test helper)."""
    return wait_ready(url, timeout_s=timeout_s) is not None


if __name__ == "__main__":
    sys.exit(serve_main())
