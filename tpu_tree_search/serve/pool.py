"""Shape-class keyed program pool — the daemon's admission control.

The resident engines already cache compiled programs per problem instance
(``problem._resident_programs`` / ``problem._mesh_programs``, keyed by
(m, M, K, capacity, device, routing token, ...)). What a one-shot CLI
cannot do is reuse them ACROSS runs: every process rebuilds its problem
and pays the while-loop compile again. The pool closes that gap by making
the problem instance itself the shared resource: requests are mapped to a
**shape class** — (problem family, shape/identity, bound variant,
knob-resolved routing token, tier, m/M/K/D/mp) — and every job of a class
runs against the same problem object, so the second same-class job finds
its program already compiled (zero recompiles, TTS_GUARD green).

Two layers of sharing fall out of the identity/class split:

  * same identity, different class (e.g. two M values) -> same problem
    instance, distinct program-cache entries — the engine's own cache key
    keeps them apart;
  * same class -> same program entry, a pure cache hit.

The class key is computed WITHOUT mutating process env: per-job knobs
(compact, lb2 pair block) are resolved through the same policy functions
the engines call at trace time (``_auto_compact``, ``_auto_pairblock``),
and server-wide routing env (pallas, staging, guard, obs) is captured once
at daemon start — the daemon's env never changes mid-flight, jobs only pin
their declared knobs through the scheduler's ``EnvLease``.
"""

from __future__ import annotations

import threading
import time


def identity_key(spec: dict) -> tuple:
    """The problem-instance identity: two specs with equal identity share
    one problem object (and therefore one program cache)."""
    if spec["problem"] == "nqueens":
        return ("nqueens", spec["N"], spec["g"])
    return ("pfsp", spec["inst"], spec["lb"], spec["ub"],
            spec.get("lb2_variant", "full"))


def server_env_token() -> tuple:
    """Server-wide routing env baked into every compiled program
    (``ops.pfsp_device.routing_cache_token`` reads these at trace time).
    Captured once per daemon: flipping them requires a restart, so they
    are part of every class key only for honesty in ``/classes`` output."""
    import os

    return tuple(
        (k, os.environ.get(k))
        for k in ("TTS_PALLAS", "TTS_PALLAS_LB2", "TTS_PALLAS_INTERPRET",
                  "TTS_LB2_STAGED", "TTS_GUARD", "TTS_OBS", "TTS_PHASEPROF",
                  "TTS_PIPELINE", "TTS_K")
    )


def _problem_shape(spec: dict) -> tuple:
    """(n, machines) without constructing the problem (host-only data)."""
    if spec["problem"] == "nqueens":
        return spec["N"], None
    from ..problems.pfsp import taillard

    return taillard.nb_jobs(spec["inst"]), taillard.nb_machines(spec["inst"])


def resolved_knobs(spec: dict) -> dict:
    """Resolve the per-job routing knobs exactly as the engines will at
    trace time, without env mutation — the knob-resolved part of the class
    token. Returns ``{"compact": mode, "lb2_pairblock": int | None}``."""
    import os

    n, machines = _problem_shape(spec)
    knob = spec.get("compact") or os.environ.get("TTS_COMPACT", "auto")
    if knob == "auto":
        from ..ops.compaction import _auto_compact

        try:
            import jax

            platform = jax.default_backend()
        except Exception:
            platform = "cpu"
        # _auto_compact only reads problem.name/initial_ub; a shim spares
        # constructing the real problem in the admission path.
        shim = type("S", (), {
            "name": spec["problem"],
            "initial_ub": 0 if spec.get("ub", 1) else (1 << 30),
        })()
        compact = _auto_compact(shim, spec["M"], n, platform)
    else:
        compact = knob
    pairblock = None
    if spec["problem"] == "pfsp" and spec["lb"] == "lb2":
        from ..ops import pfsp_device as P
        from ..problems.pfsp import bounds as PB

        Pn = len(PB.machine_pairs(machines, spec.get("lb2_variant", "full")))
        pb = spec.get("lb2_pairblock") or os.environ.get(
            "TTS_LB2_PAIRBLOCK", "auto"
        )
        if pb == "auto":
            pairblock = P._auto_pairblock(Pn, n)
        else:
            pairblock = min(int(pb), Pn)
    return {"compact": compact, "lb2_pairblock": pairblock}


def class_key(spec: dict) -> str:
    """The human-readable shape-class token. Everything that selects a
    distinct compiled program is in here; two jobs with equal keys hit the
    same program-cache entry."""
    ident = identity_key(spec)
    knobs = resolved_knobs(spec)
    parts = ["-".join(str(p) for p in ident), spec["tier"],
             f"m{spec['m']}", f"M{spec['M']}"]
    if spec.get("K") is not None:
        parts.append(f"K{spec['K']}")
    if spec["tier"] == "mesh":
        parts.append(f"D{spec.get('D', 'all')}")
        if spec.get("mp", 1) != 1:
            parts.append(f"mp{spec['mp']}")
    parts.append(f"compact={knobs['compact']}")
    if knobs["lb2_pairblock"] is not None:
        parts.append(f"pb{knobs['lb2_pairblock']}")
    return "-".join(parts)


def compile_stats(problem) -> tuple[int, int]:
    """(program entries, jit step-cache entries) currently compiled on a
    problem instance — the pool's recompile accounting unit. Measured
    around each job slice: a warm-class admission must leave both deltas
    at zero (the serve analogue of the TTS_GUARD steady-state assertion,
    and the number `tts warmup` reports as hit/miss)."""
    from ..analysis.guard import _cache_size

    progs = 0
    steps = 0
    for attr in ("_resident_programs", "_mesh_programs",
                 "_batched_programs"):
        # Snapshot: a scheduler worker may be inserting a program while a
        # stats request iterates (len+list are atomic under the GIL).
        cache = list((getattr(problem, attr, None) or {}).values())
        progs += len(cache)
        for prog in cache:
            size = _cache_size(getattr(prog, "_step", None))
            if size is not None:
                steps += size
    return progs, steps


def resident_pool_bytes(problem) -> int:
    """Device-resident pool bytes across every program cached on a
    problem instance — capacity x per-node pool bytes, times the slot
    (B) / shard (D) count for the batched and mesh programs. Read at
    scrape time for the `tts_serve_pool_bytes{cls}` gauge: the number
    that shrinks when narrow node storage (TTS_NARROW) lands, and the
    per-class HBM footprint an operator sizes co-tenancy against."""
    import numpy as np

    total = 0
    for attr in ("_resident_programs", "_mesh_programs",
                 "_batched_programs"):
        cache = list((getattr(problem, attr, None) or {}).values())
        for prog in cache:
            inner = getattr(prog, "inner", prog)
            fields = getattr(inner, "pool_fields", None)
            cap = getattr(inner, "capacity", None)
            if fields is None or cap is None:
                continue
            copies = int(getattr(prog, "B", 0) or getattr(prog, "D", 0) or 1)
            per_node = sum(
                int(np.prod(shape, dtype=np.int64)) * np.dtype(dt).itemsize
                for _name, dt, shape in fields
            )
            total += copies * int(cap) * per_node
    return total


class ClassEntry:
    """One shape class: the shared problem instance plus admission
    bookkeeping. ``warm`` flips after the first job of the class has
    compiled-and-run — later admissions are promised zero recompiles."""

    def __init__(self, key: str, spec: dict, problem):
        self.key = key
        self.spec = dict(spec)  # the first admitting spec (class exemplar)
        self.problem = problem
        self.created = time.time()
        self.jobs_admitted = 0
        self.warm = False

    def stats(self) -> dict:
        progs, steps = compile_stats(self.problem)
        return {
            "class": self.key,
            "jobs_admitted": self.jobs_admitted,
            "warm": self.warm,
            "programs": progs,
            "step_cache_entries": steps,
            "pool_bytes": resident_pool_bytes(self.problem),
        }


class ProgramPool:
    """class key -> ClassEntry, with identity-level problem sharing."""

    def __init__(self):
        self._lock = threading.Lock()
        self._classes = {}  # guarded-by: _lock
        self._problems = {}  # guarded-by: _lock  (identity -> problem)
        self.server_token = server_env_token()

    def admit(self, spec: dict) -> ClassEntry:
        """Map a validated spec to its class entry, constructing the
        shared problem on first contact. Called by the scheduler (jax
        side); the constructor runs under the lock — problem construction
        is host-only table building, never a device compile."""
        key = class_key(spec)
        with self._lock:
            entry = self._classes.get(key)
            if entry is None:
                ident = identity_key(spec)
                problem = self._problems.get(ident)
                if problem is None:
                    from .jobs import build_problem

                    problem = build_problem(spec)
                    self._problems[ident] = problem
                entry = ClassEntry(key, spec, problem)
                self._classes[key] = entry
            entry.jobs_admitted += 1
            return entry

    def peek(self, spec: dict) -> dict:
        """Admission-time class info for the submit response (HTTP thread;
        must not build problems): the key plus whether it is already warm."""
        key = class_key(spec)
        with self._lock:
            entry = self._classes.get(key)
            return {"class": key, "warm": entry.warm if entry else False}

    def mark_warm(self, entry: ClassEntry) -> None:
        with self._lock:
            entry.warm = True

    def stats(self) -> list[dict]:
        with self._lock:
            entries = list(self._classes.values())
        return [e.stats() for e in entries]
