"""``tts serve`` — persistent multi-tenant search daemon.

The serve package turns the one-shot CLI into a resident service
(ROADMAP item 2, the search-as-a-service direction of arXiv:2002.07062):
one long-lived process owns the accelerator, admits search jobs over a
localhost HTTP/JSON API, and keeps every compiled program alive between
jobs so repeat work pays zero compile seconds.

Layout (each module owns one concern):

  * ``jobs.py``      — job specs (validated JSON), the Job record, and the
    durable on-disk registry (submit/status/result survive a restart);
  * ``pool.py``      — shape-class admission control: requests map to a
    (problem family, shape, bound variant, knob-resolved token) class and
    share one problem instance per identity, so a second same-class job
    admits with **zero recompiles** (TTS_GUARD green);
  * ``scheduler.py`` — worker threads + checkpoint-based preemption
    (``RunController`` ``yield_fn`` drain -> cut -> resume, bit-identical)
    and the env-knob lease that serializes conflicting per-job pins;
  * ``batch.py``     — the instance-axis batch executor: with
    ``--batch-slots B`` one compiled program advances up to B same-class
    jobs per K-cycle dispatch, splicing/retiring jobs at dispatch
    boundaries with zero recompiles (engine/batched.py);
  * ``server.py``    — the stdlib HTTP/SSE daemon (same zero-dep pattern
    as ``obs/live.py``) and graceful SIGTERM drain;
  * ``client.py``    — ``tts submit`` / ``tts watch --job`` thin clients;
  * ``warmup.py``    — the AOT warm matrix (``scripts/warm_cache.py``
    promoted to an importable module) + per-class hit/miss reporting.

Everything is stdlib-only on the serving path; jax is imported lazily by
the scheduler workers, never by the clients.
"""

from __future__ import annotations

DEFAULT_PORT = 8643  # one above obs/live's default watch port

#: Daemon version, surfaced on ``/healthz`` and ``/metrics``
#: (``tts_serve_build_info``) so fleet tooling can tell which daemons
#: still need a rolling restart. Bump when the HTTP API or job-record
#: schema changes.
VERSION = "0.13.0"

__all__ = ["DEFAULT_PORT", "VERSION"]
