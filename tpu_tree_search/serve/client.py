"""Thin clients for the serve daemon: ``tts submit`` / ``tts watch --job``
/ ``tts top``.

Pure stdlib HTTP (urllib) against 127.0.0.1 — no jax import on any path
here, same discipline as ``obs/live.watch_main``. The submit client
converts CLI run arguments into a job spec (reusing the main parser's
validation via ``tts submit -- <run args>``), posts it, and either
returns the id immediately or follows the job's SSE stream to completion.
``tts top`` is the operator console: a periodically refreshed per-job /
per-class table assembled from ``/healthz`` + ``/jobs`` + ``/classes``.
"""

from __future__ import annotations

import json
import sys
import time
from urllib.error import HTTPError, URLError
from urllib.request import Request, urlopen

from ..obs.live import format_snapshot, iter_sse
from . import DEFAULT_PORT

_FINAL = ("done", "failed", "cancelled")


def _retrying(do, retry_s: float):
    """Run ``do()`` retrying transient transport failures (connection
    refused/reset during a daemon restart, socket timeouts) with
    exponential backoff until the ``retry_s`` deadline, then re-raise.
    An ``HTTPError`` is never retried here — a status line IS an answer;
    callers branch on the code. ``retry_s=0`` keeps the old single-shot
    behaviour."""
    deadline = time.monotonic() + max(0.0, retry_s)
    delay = 0.1
    while True:
        try:
            return do()
        except HTTPError:
            raise
        except (URLError, OSError, ConnectionError):
            if time.monotonic() + delay > deadline:
                raise
            time.sleep(delay)
            delay = min(2.0, delay * 2)


def _post(url: str, payload: dict, timeout: float = 10.0,
          retry_s: float = 0.0) -> tuple[int, dict]:
    body = json.dumps(payload).encode()
    req = Request(url, data=body,
                  headers={"Content-Type": "application/json"})

    def do():
        with urlopen(req, timeout=timeout) as resp:  # noqa: S310 — localhost
            return resp.status, json.loads(resp.read().decode())

    try:
        return _retrying(do, retry_s)
    except HTTPError as e:
        try:
            return e.code, json.loads(e.read().decode())
        except ValueError:
            return e.code, {"error": str(e)}


def _get(url: str, timeout: float = 10.0,
         retry_s: float = 0.0) -> tuple[int, dict]:
    def do():
        with urlopen(url, timeout=timeout) as resp:  # noqa: S310
            return resp.status, json.loads(resp.read().decode())

    try:
        return _retrying(do, retry_s)
    except HTTPError as e:
        try:
            return e.code, json.loads(e.read().decode())
        except ValueError:
            return e.code, {"error": str(e)}


def fetch_checkpoint(base: str, jid: str, timeout: float = 30.0,
                     retry_s: float = 0.0) -> tuple[bytes, int]:
    """``GET /job/<id>/checkpoint`` with gzip transport negotiated:
    returns ``(npz bytes, wire bytes)``. Shared by ``tts migrate`` and
    the fleet router's checkpoint pulls. Raises ``HTTPError`` (409: no
    checkpoint yet) or ``URLError`` past the retry deadline."""

    def do():
        # Ask for gzip transport: urllib neither advertises nor decodes
        # it on its own, so both ends are explicit here. Old daemons
        # ignore the header and send identity — both shapes are handled.
        req = Request(base + f"/job/{jid}/checkpoint",
                      headers={"Accept-Encoding": "gzip"})
        with urlopen(req, timeout=timeout) as resp:  # noqa: S310
            raw = resp.read()
            wire = len(raw)
            if resp.headers.get("Content-Encoding") == "gzip":
                import gzip

                raw = gzip.decompress(raw)
            return raw, wire

    return _retrying(do, retry_s)


def spec_from_args(args) -> dict:
    """A job spec from parsed CLI run arguments (the submit path re-parses
    ``<run args>`` through ``cli.build_parser`` first, so every CLI-side
    validation already ran)."""
    # The run parser defaults to --tier seq; the daemon only runs the
    # preemptible resident tiers, so an unspecified/seq tier submits as
    # the device tier (the daemon's natural unit of work).
    tier = "device" if args.tier == "seq" else args.tier
    spec = {"problem": args.problem, "tier": tier, "m": args.m}
    if args.M is not None:
        spec["M"] = args.M
    if args.K is not None:
        spec["K"] = args.K
    if args.problem == "nqueens":
        spec.update(N=args.N, g=args.g)
    else:
        spec.update(inst=args.inst, lb=args.lb, ub=args.ub)
        if args.lb2_variant != "full":
            spec["lb2_variant"] = args.lb2_variant
        if args.lb2_pairblock is not None:
            pb = args.lb2_pairblock
            spec["lb2_pairblock"] = pb if pb == "auto" else int(pb)
    if args.tier == "mesh":
        if args.D is not None:
            spec["D"] = args.D
        if args.mp != 1:
            spec["mp"] = args.mp
    if args.compact is not None:
        spec["compact"] = args.compact
    if args.max_steps is not None:
        spec["max_steps"] = args.max_steps
    return spec


def base_url(port: int = DEFAULT_PORT, host: str = "127.0.0.1",
             router: str | None = None) -> str:
    """The client's target base URL: the router when ``--router`` (or
    TTS_ROUTER) names one — every serve endpoint the clients use is
    proxied 1:1 by the fleet router — else the daemon at host:port."""
    if router:
        router = router.rstrip("/")
        return router if "://" in router else "http://" + router
    return f"http://{host}:{port}"


def submit_main(spec: dict, port: int = DEFAULT_PORT,
                host: str = "127.0.0.1", wait: bool = False,
                as_json: bool = False, router: str | None = None,
                retry_s: float = 10.0) -> int:
    """Submit a job; with ``wait`` follow it to completion (result record
    printed — the serve analogue of a ``tts run --json`` line). The
    submit POST retries transient connection failures for ``retry_s``
    (a restarting daemon/router is a routine fleet event, not an
    error)."""
    base = base_url(port, host, router)
    try:
        code, payload = _post(base + "/submit", spec, retry_s=retry_s)
    except (URLError, OSError) as e:
        print(f"Error: no serve daemon at {base}: {e}", file=sys.stderr)
        return 2
    if code != 201:
        print(f"Error: submit rejected ({code}): "
              f"{payload.get('error', payload)}{_daemon_tag(base)}",
              file=sys.stderr)
        return 2
    if not wait:
        if as_json:
            print(json.dumps(payload))
        else:
            print(f"{payload['id']}  class={payload['class']}"
                  f"{' (warm)' if payload.get('warm') else ''}"
                  f"  position={payload['position']}"
                  + (f"  @ {payload['daemon']}"  # routed by a fleet router
                     if payload.get("daemon") else ""))
        return 0
    rec = follow_job(base, payload["id"],
                     emit=None if as_json else
                     (lambda s: print(format_snapshot(s), flush=True)),
                     on_incumbent=None if as_json else
                     (lambda p: print(_format_incumbent(p), flush=True)))
    if rec is None:
        print(f"Error: lost job {payload['id']}", file=sys.stderr)
        return 2
    if as_json:
        print(json.dumps(rec))
    else:
        _print_final(rec)
    return 0 if rec.get("state") == "done" else 1


def _daemon_tag(base: str) -> str:
    """`` [daemon v0.11.0, up 42s, workers 1/1 alive]`` for error
    messages — a rejected submit should say WHICH daemon rejected it and
    whether its workers are even running (a dead worker thread otherwise
    hides behind a listening socket)."""
    try:
        code, h = _get(base + "/healthz", timeout=2.0)
    except (URLError, OSError):
        return ""
    if code != 200 or not isinstance(h, dict):
        return ""
    return (f" [daemon v{h.get('version', '?')}, "
            f"up {h.get('uptime_s', 0):.0f}s, "
            f"workers {h.get('workers_alive', '?')}/{h.get('workers', '?')}"
            f" alive]")


def _format_incumbent(p: dict) -> str:
    """One human line per quality-trajectory improvement."""
    return (f"  incumbent #{p.get('n', '?')}: best={p.get('best')}"
            f"  t={p.get('t_s', 0.0):.3f}s  step={p.get('step')}"
            f"  nodes={p.get('nodes')}")


def _print_final(rec: dict) -> None:
    res = rec.get("result") or {}
    print(f"{rec['id']}: {rec['state']}"
          + (f"  tree={res.get('explored_tree')} "
             f"sol={res.get('explored_sol')} best={res.get('best')}"
             if res else "")
          + (f"  error={rec['error']}" if rec.get("error") else ""))


def follow_job(base: str, jid: str, emit=None, timeout_s: float = 600.0,
               on_incumbent=None):
    """Stream a job's SSE until its ``done`` frame; fall back to polling
    if the stream drops (daemon restart). Returns the final job record or
    None. ``on_incumbent`` receives each NEW ``event: incumbent`` quality
    frame (deduped by its monotone ``n`` index across reconnects).

    Dedupe: the server re-sends a job's latest snapshot (and every
    incumbent so far) on each NEW stream connection, so this reconnect
    loop would re-print identical frames once per retry interval on a
    quiet job. Snapshots are deduped by their ``(ts_us, seq)`` identity,
    incumbents by ``n`` — both survive any number of reconnects."""
    deadline = time.monotonic() + timeout_s
    last_key = None  # (ts_us, seq) of the last emitted snapshot
    max_n = 0  # highest incumbent index emitted
    while time.monotonic() < deadline:
        try:
            req = base + f"/job/{jid}/stream"
            with urlopen(req, timeout=timeout_s) as resp:  # noqa: S310
                for event, payload in iter_sse(resp):
                    if event == "done":
                        return payload
                    if event == "incumbent":
                        n = int(payload.get("n") or 0)
                        if n and n <= max_n:
                            continue  # reconnect replayed an old frame
                        max_n = max(max_n, n)
                        if on_incumbent is not None:
                            on_incumbent(payload)
                        continue
                    key = (payload.get("ts_us"), payload.get("seq"))
                    if key == last_key:
                        continue
                    last_key = key
                    if emit is not None:
                        emit(payload)
        except (OSError, ValueError):
            pass
        # Stream dropped: poll the record directly. The poll itself
        # rides the retry helper — a daemon restarting (or a router
        # recovering the job onto another daemon) answers again within
        # seconds, and a watch must survive that window instead of
        # reporting the job lost.
        try:
            code, rec = _get(base + f"/job/{jid}", retry_s=10.0)
        except (URLError, OSError):
            time.sleep(0.5)
            continue
        if code == 200 and rec.get("state") in _FINAL:
            return rec
        if code == 404:
            return None
        time.sleep(0.5)
    return None


def watch_job_main(jid: str, port: int = DEFAULT_PORT,
                   host: str = "127.0.0.1", once: bool = False,
                   as_json: bool = False,
                   max_updates: int | None = None) -> int:
    """``tts watch --job <id>``: live per-job stream from the daemon."""
    base = f"http://{host}:{port}"
    try:
        code, rec = _get(base + f"/job/{jid}")
    except URLError as e:
        print(f"Error: no serve daemon at {base}: {e}", file=sys.stderr)
        return 2
    if code != 200:
        print(f"Error: unknown job {jid}", file=sys.stderr)
        return 2
    emit = (lambda s: print(json.dumps(s), flush=True)) if as_json else (
        lambda s: print(format_snapshot(s), flush=True)
    )
    if once or rec.get("state") in _FINAL:
        if as_json:
            print(json.dumps(rec))
        else:
            _print_final(rec) if rec.get("state") in _FINAL else print(
                f"{rec['id']}: {rec['state']}"
            )
        return 0
    # Delegate to follow_job: it owns the reconnect/poll fallback AND the
    # cross-reconnect dedupe (the old inline loop re-printed the latest
    # snapshot after every stream drop).
    seen = {"n": 0}

    def bounded_emit(s):
        emit(s)
        seen["n"] += 1
        if max_updates is not None and seen["n"] >= max_updates:
            raise _Enough

    on_inc = ((lambda p: print(json.dumps({"incumbent": p}), flush=True))
              if as_json else
              (lambda p: print(_format_incumbent(p), flush=True)))
    try:
        final = follow_job(base, jid, emit=bounded_emit,
                           on_incumbent=on_inc)
    except (_Enough, KeyboardInterrupt):
        return 0
    if final is None:
        print(f"Error: lost job {jid}", file=sys.stderr)
        return 2
    if as_json:
        print(json.dumps(final))
    else:
        _print_final(final)
    return 0


class _Enough(Exception):
    """Raised by a bounded watch to cut the stream after --max-updates."""


# -- the `tts top` operator console ------------------------------------------


def _fmt_bytes(n) -> str:
    """Human bytes for the per-class pool column (0 -> '-': nothing
    resident yet, e.g. the class is admitted but not compiled)."""
    n = float(int(n or 0))
    if n <= 0:
        return "-"
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return f"{n:.0f}{unit}" if unit == "B" else f"{n:.1f}{unit}"
        n /= 1024
    return f"{n:.1f}GiB"


def _render_top(health: dict, jobs: list, classes: dict) -> str:
    """The ``tts top`` display: daemon header, per-class occupancy table,
    then per-job rows (active work first, newest terminal jobs last)."""
    lines = []
    ok = health.get("ok", False)
    lines.append(
        f"tts serve v{health.get('version', '?')}"
        f"  up {health.get('uptime_s', 0):.0f}s"
        f"  queue={health.get('queue_depth', 0)}"
        f"  workers={health.get('workers_alive', '?')}"
        f"/{health.get('workers', '?')}"
        + (f"  batch={health['batch_slots']}"
           if int(health.get("batch_slots") or 1) > 1 else "")
        + ("" if ok else "  [DEGRADED: no alive worker]")
    )
    by_state: dict = {}
    for j in jobs:
        by_state[j.get("state", "?")] = by_state.get(j.get("state", "?"), 0) + 1
    lines.append("jobs: " + ("  ".join(
        f"{s}={n}" for s, n in sorted(by_state.items())) or "none"))
    if classes:
        lines.append("")
        lines.append(f"{'class':<44} {'warm':>4} {'progs':>5} "
                     f"{'steps':>5} {'jobs':>5} {'slots':>5} {'pool':>8}")
        for st in sorted(classes, key=lambda st: st.get("class", "")):
            if "slots_occupied" in st:
                slots = f"{st['slots_occupied']}/{st.get('batch_slots', '?')}"
            else:
                slots = "-"
            lines.append(
                f"{(st.get('class') or '?')[:44]:<44} "
                f"{'y' if st.get('warm') else '-':>4} "
                f"{st.get('programs', 0):>5} "
                f"{st.get('step_cache_entries', 0):>5} "
                f"{st.get('jobs_admitted', 0):>5} "
                f"{slots:>5} "
                f"{_fmt_bytes(st.get('pool_bytes', 0)):>8}")
    active = [j for j in jobs
              if j.get("state") in ("running", "queued", "requeued")]
    finished = [j for j in jobs if j not in active]
    rows = active + finished[-5:]  # full active set + recent history
    if rows:
        lines.append("")
        lines.append(f"{'job':<12} {'state':<9} {'class':<36} "
                     f"{'slices':>6} {'preempt':>7} {'steps':>9} {'best':>8}")
        for j in rows:
            res = j.get("result") or {}
            q = (res.get("quality") or {}).get("points") or []
            best = res.get("best", q[-1]["best"] if q else None)
            lines.append(
                f"{j.get('id', '?'):<12} {j.get('state', '?'):<9} "
                f"{(j.get('class') or '?')[:36]:<36} "
                f"{j.get('slices', 0):>6} {j.get('preemptions', 0):>7} "
                f"{j.get('steps', 0):>9} "
                f"{best if best is not None else '-':>8}")
    return "\n".join(lines)


def top_main(port: int = DEFAULT_PORT, host: str = "127.0.0.1",
             interval: float = 2.0, once: bool = False,
             as_json: bool = False) -> int:
    """``tts top``: live per-job / per-class daemon table (the serve
    analogue of ``tts watch``'s single-run status line). ``--once``
    prints one frame and exits (CI smoke); ``--json`` emits the raw
    composed payload per refresh."""
    base = f"http://{host}:{port}"
    try:
        while True:
            try:
                _, health = _get(base + "/healthz", timeout=5.0)
                _, jobs = _get(base + "/jobs", timeout=5.0)
                _, classes = _get(base + "/classes", timeout=5.0)
            except (URLError, OSError) as e:
                print(f"Error: no serve daemon at {base}: {e}",
                      file=sys.stderr)
                return 2
            if as_json:
                print(json.dumps({"health": health, "jobs": jobs,
                                  "classes": classes}), flush=True)
            else:
                if not once and sys.stdout.isatty():
                    print("\x1b[2J\x1b[H", end="")
                print(_render_top(health, jobs, classes), flush=True)
            if once:
                return 0
            time.sleep(interval)
    except KeyboardInterrupt:
        return 0


# -- `tts top --router`: the fleet-wide operator console ----------------------


def _render_fleet_top(fleet: dict) -> str:
    """Per-daemon rows + fleet totals from the router's ``/fleet``
    aggregate (its keeper's last ``/healthz`` + ``/classes`` scrape of
    every registered daemon)."""
    router = fleet.get("router") or {}
    daemons = fleet.get("daemons") or []
    jobs = fleet.get("jobs") or []
    lines = [
        f"tts fleet v{router.get('version', '?')}"
        f"  up {router.get('uptime_s', 0):.0f}s"
        f"  daemons={router.get('daemons_healthy', 0)}"
        f"/{router.get('daemons', 0)} healthy"
        f"  jobs={router.get('jobs', 0)}"
        + ("" if router.get("ok") else "  [DEGRADED: no healthy daemon]")
    ]
    lines.append("")
    lines.append(f"{'daemon':<28} {'state':<8} {'queue':>5} {'work':>6} "
                 f"{'warm':>4} {'cls':>3} {'pool':>8} {'jobs':<24}")
    tot_queue = tot_warm = tot_cls = tot_pool = 0
    for d in daemons:
        h = d.get("health") or {}
        classes = d.get("classes") or []
        warm = sum(1 for c in classes if c.get("warm"))
        pool = sum(int(c.get("pool_bytes", 0) or 0) for c in classes)
        state = ("drain" if d.get("draining")
                 else "ok" if d.get("healthy")
                 else f"dead({d.get('misses', 0)})")
        by_state = d.get("jobs_by_state") or {}
        tot_queue += int(h.get("queue_depth", 0) or 0)
        tot_warm += warm
        tot_cls += len(classes)
        tot_pool += pool
        lines.append(
            f"{d.get('url', '?')[:28]:<28} {state:<8} "
            f"{h.get('queue_depth', 0):>5} "
            f"{h.get('workers_alive', '?')}/{h.get('workers', '?'):>4} "
            f"{warm:>4} {len(classes):>3} {_fmt_bytes(pool):>8} "
            + (" ".join(f"{s}={n}" for s, n in sorted(by_state.items()))
               or "-"))
    lines.append(
        f"{'TOTAL':<28} {'':<8} {tot_queue:>5} {'':>6} "
        f"{tot_warm:>4} {tot_cls:>3} {_fmt_bytes(tot_pool):>8}")
    active = [j for j in jobs
              if j.get("state") not in _FINAL]
    finished = [j for j in jobs if j not in active]
    rows = active + finished[-5:]
    if rows:
        lines.append("")
        lines.append(f"{'fleet job':<12} {'state':<9} {'daemon':<24} "
                     f"{'class':<30} {'steps':>8} {'moves':>5}")
        for j in rows:
            lines.append(
                f"{j.get('id', '?'):<12} {j.get('state') or '?':<9} "
                f"{(j.get('daemon') or '?')[:24]:<24} "
                f"{(j.get('class') or '?')[:30]:<30} "
                f"{j.get('steps', 0):>8} {j.get('resubmits', 0):>5}")
    return "\n".join(lines)


def fleet_top_main(router: str, interval: float = 2.0, once: bool = False,
                   as_json: bool = False) -> int:
    """``tts top --router URL``: the fleet-wide console — per-daemon
    rows aggregated from the router keeper's scrapes plus fleet totals.
    ``--once``/``--json`` mirror the single-daemon ``tts top`` (CI
    smoke)."""
    base = base_url(router=router)
    try:
        while True:
            try:
                code, fleet = _get(base + "/fleet", timeout=5.0,
                                   retry_s=5.0)
            except (URLError, OSError) as e:
                print(f"Error: no fleet router at {base}: {e}",
                      file=sys.stderr)
                return 2
            if code != 200:
                print(f"Error: /fleet failed ({code}): {fleet}",
                      file=sys.stderr)
                return 2
            if as_json:
                print(json.dumps(fleet), flush=True)
            else:
                if not once and sys.stdout.isatty():
                    print("\x1b[2J\x1b[H", end="")
                print(_render_fleet_top(fleet), flush=True)
            if once:
                return 0
            time.sleep(interval)
    except KeyboardInterrupt:
        return 0


# -- `tts migrate`: cross-daemon job migration --------------------------------


def migrate_main(jid: str, to_url: str, port: int = DEFAULT_PORT,
                 host: str = "127.0.0.1", as_json: bool = False,
                 timeout_s: float = 120.0) -> int:
    """``tts migrate <job> --to URL``: move a job between daemons over its
    portable checkpoint. Cancel on daemon A (cutting a running slice at
    the next dispatch boundary), fetch the checkpoint bytes, resubmit the
    spec + checkpoint to daemon B — counters stay cumulative, so the
    migrated run's final result is bit-identical to never having moved.
    A consumed ``max_steps`` budget follows the job: the resubmitted spec
    carries only the remaining steps."""
    base = f"http://{host}:{port}"
    dst = to_url.rstrip("/")
    if "://" not in dst:
        dst = "http://" + dst
    try:
        code, rec = _get(base + f"/job/{jid}")
    except URLError as e:
        print(f"Error: no serve daemon at {base}: {e}", file=sys.stderr)
        return 2
    if code != 200:
        print(f"Error: unknown job {jid}", file=sys.stderr)
        return 2
    if rec.get("state") in ("queued", "requeued", "running"):
        code, resp = _post(base + f"/job/{jid}/cancel", {})
        if code not in (200, 409):
            print(f"Error: cancel failed ({code}): {resp}", file=sys.stderr)
            return 2
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            code, rec = _get(base + f"/job/{jid}")
            if code == 200 and rec.get("state") in _FINAL:
                break
            time.sleep(0.2)
    if rec.get("state") == "done":
        print(f"{jid} already finished on {base}; nothing to migrate",
              file=sys.stderr)
        return 1
    if not rec.get("checkpoint"):
        print(f"Error: {jid} has no checkpoint to migrate "
              f"(state {rec.get('state')}; it never ran to a cut)",
              file=sys.stderr)
        return 2
    try:
        raw, wire_bytes = fetch_checkpoint(base, jid)
    except (URLError, OSError) as e:
        print(f"Error: checkpoint fetch failed: {e}", file=sys.stderr)
        return 2
    spec = dict(rec.get("spec") or {})
    steps = int(rec.get("steps") or 0)
    if spec.get("max_steps") is not None:
        remaining = int(spec["max_steps"]) - steps
        if remaining <= 0:
            print(f"Error: {jid} already exhausted its max_steps budget "
                  f"({steps}/{spec['max_steps']})", file=sys.stderr)
            return 2
        spec["max_steps"] = remaining
    import base64

    payload = {**spec, "resume_ckpt_b64": base64.b64encode(raw).decode()}
    try:
        code, sub = _post(dst + "/submit", payload, timeout=60.0)
    except URLError as e:
        print(f"Error: no serve daemon at {dst}: {e}", file=sys.stderr)
        return 2
    if code != 201:
        print(f"Error: destination rejected the migrated job ({code}): "
              f"{sub.get('error', sub)}{_daemon_tag(dst)}", file=sys.stderr)
        return 2
    if as_json:
        print(json.dumps({"from": jid, "id": sub["id"], "to": dst,
                          "class": sub.get("class"),
                          "warm": sub.get("warm"), "steps_done": steps,
                          "ckpt_bytes": len(raw),
                          "ckpt_wire_bytes": wire_bytes}))
    else:
        print(f"{jid} -> {sub['id']} @ {dst}  class={sub.get('class')}"
              f"{' (warm)' if sub.get('warm') else ''}"
              f"  steps_done={steps}"
              f"  ckpt={len(raw)}B"
              + (f" (gzip wire {wire_bytes}B)"
                 if wire_bytes != len(raw) else ""))
    return 0
