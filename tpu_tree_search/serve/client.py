"""Thin clients for the serve daemon: ``tts submit`` / ``tts watch --job``.

Pure stdlib HTTP (urllib) against 127.0.0.1 — no jax import on any path
here, same discipline as ``obs/live.watch_main``. The submit client
converts CLI run arguments into a job spec (reusing the main parser's
validation via ``tts submit -- <run args>``), posts it, and either
returns the id immediately or follows the job's SSE stream to completion.
"""

from __future__ import annotations

import json
import sys
import time
from urllib.error import HTTPError, URLError
from urllib.request import Request, urlopen

from ..obs.live import format_snapshot, iter_sse
from . import DEFAULT_PORT

_FINAL = ("done", "failed", "cancelled")


def _post(url: str, payload: dict, timeout: float = 10.0) -> tuple[int, dict]:
    body = json.dumps(payload).encode()
    req = Request(url, data=body,
                  headers={"Content-Type": "application/json"})
    try:
        with urlopen(req, timeout=timeout) as resp:  # noqa: S310 — localhost
            return resp.status, json.loads(resp.read().decode())
    except HTTPError as e:
        try:
            return e.code, json.loads(e.read().decode())
        except ValueError:
            return e.code, {"error": str(e)}


def _get(url: str, timeout: float = 10.0) -> tuple[int, dict]:
    try:
        with urlopen(url, timeout=timeout) as resp:  # noqa: S310
            return resp.status, json.loads(resp.read().decode())
    except HTTPError as e:
        try:
            return e.code, json.loads(e.read().decode())
        except ValueError:
            return e.code, {"error": str(e)}


def spec_from_args(args) -> dict:
    """A job spec from parsed CLI run arguments (the submit path re-parses
    ``<run args>`` through ``cli.build_parser`` first, so every CLI-side
    validation already ran)."""
    # The run parser defaults to --tier seq; the daemon only runs the
    # preemptible resident tiers, so an unspecified/seq tier submits as
    # the device tier (the daemon's natural unit of work).
    tier = "device" if args.tier == "seq" else args.tier
    spec = {"problem": args.problem, "tier": tier, "m": args.m}
    if args.M is not None:
        spec["M"] = args.M
    if args.K is not None:
        spec["K"] = args.K
    if args.problem == "nqueens":
        spec.update(N=args.N, g=args.g)
    else:
        spec.update(inst=args.inst, lb=args.lb, ub=args.ub)
        if args.lb2_variant != "full":
            spec["lb2_variant"] = args.lb2_variant
        if args.lb2_pairblock is not None:
            pb = args.lb2_pairblock
            spec["lb2_pairblock"] = pb if pb == "auto" else int(pb)
    if args.tier == "mesh":
        if args.D is not None:
            spec["D"] = args.D
        if args.mp != 1:
            spec["mp"] = args.mp
    if args.compact is not None:
        spec["compact"] = args.compact
    if args.max_steps is not None:
        spec["max_steps"] = args.max_steps
    return spec


def submit_main(spec: dict, port: int = DEFAULT_PORT,
                host: str = "127.0.0.1", wait: bool = False,
                as_json: bool = False) -> int:
    """Submit a job; with ``wait`` follow it to completion (result record
    printed — the serve analogue of a ``tts run --json`` line)."""
    base = f"http://{host}:{port}"
    try:
        code, payload = _post(base + "/submit", spec)
    except URLError as e:
        print(f"Error: no serve daemon at {base}: {e}", file=sys.stderr)
        return 2
    if code != 201:
        print(f"Error: submit rejected ({code}): "
              f"{payload.get('error', payload)}", file=sys.stderr)
        return 2
    if not wait:
        if as_json:
            print(json.dumps(payload))
        else:
            print(f"{payload['id']}  class={payload['class']}"
                  f"{' (warm)' if payload.get('warm') else ''}"
                  f"  position={payload['position']}")
        return 0
    rec = follow_job(base, payload["id"],
                     emit=None if as_json else
                     (lambda s: print(format_snapshot(s), flush=True)))
    if rec is None:
        print(f"Error: lost job {payload['id']}", file=sys.stderr)
        return 2
    if as_json:
        print(json.dumps(rec))
    else:
        _print_final(rec)
    return 0 if rec.get("state") == "done" else 1


def _print_final(rec: dict) -> None:
    res = rec.get("result") or {}
    print(f"{rec['id']}: {rec['state']}"
          + (f"  tree={res.get('explored_tree')} "
             f"sol={res.get('explored_sol')} best={res.get('best')}"
             if res else "")
          + (f"  error={rec['error']}" if rec.get("error") else ""))


def follow_job(base: str, jid: str, emit=None, timeout_s: float = 600.0):
    """Stream a job's SSE until its ``done`` frame; fall back to polling
    if the stream drops (daemon restart). Returns the final job record or
    None."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            req = base + f"/job/{jid}/stream"
            with urlopen(req, timeout=timeout_s) as resp:  # noqa: S310
                for event, payload in iter_sse(resp):
                    if event == "done":
                        return payload
                    if emit is not None:
                        emit(payload)
        except (OSError, ValueError):
            pass
        # Stream dropped: poll the record directly.
        try:
            code, rec = _get(base + f"/job/{jid}")
        except URLError:
            return None
        if code == 200 and rec.get("state") in _FINAL:
            return rec
        if code == 404:
            return None
        time.sleep(0.5)
    return None


def watch_job_main(jid: str, port: int = DEFAULT_PORT,
                   host: str = "127.0.0.1", once: bool = False,
                   as_json: bool = False,
                   max_updates: int | None = None) -> int:
    """``tts watch --job <id>``: live per-job stream from the daemon."""
    base = f"http://{host}:{port}"
    try:
        code, rec = _get(base + f"/job/{jid}")
    except URLError as e:
        print(f"Error: no serve daemon at {base}: {e}", file=sys.stderr)
        return 2
    if code != 200:
        print(f"Error: unknown job {jid}", file=sys.stderr)
        return 2
    emit = (lambda s: print(json.dumps(s), flush=True)) if as_json else (
        lambda s: print(format_snapshot(s), flush=True)
    )
    if once or rec.get("state") in _FINAL:
        if as_json:
            print(json.dumps(rec))
        else:
            _print_final(rec) if rec.get("state") in _FINAL else print(
                f"{rec['id']}: {rec['state']}"
            )
        return 0
    seen = 0
    try:
        req = base + f"/job/{jid}/stream"
        with urlopen(req, timeout=600.0) as resp:  # noqa: S310
            for event, payload in iter_sse(resp):
                if event == "done":
                    if as_json:
                        print(json.dumps(payload))
                    else:
                        _print_final(payload)
                    return 0
                emit(payload)
                seen += 1
                if max_updates is not None and seen >= max_updates:
                    return 0
    except KeyboardInterrupt:
        return 0
    except OSError as e:
        if seen == 0:
            print(f"Error: stream failed: {e}", file=sys.stderr)
            return 2
    return 0
