"""``tpu_tree_search.obs`` — guard-safe telemetry.

Three legs (see docs/OBSERVABILITY.md):

  * ``counters`` — on-device cycle counters: a fixed-shape int32 block in
    the resident loop carry, accumulated inside the jitted
    ``lax.while_loop`` and harvested at the existing K-cycle dispatch
    boundaries. Compiled out entirely (byte-identical jaxpr) when off.
  * ``events`` — host event tracing: thread-local buffers + merge, wired
    through every runtime (dispatches, steals, exchange rounds, incumbent
    improvements, checkpoint cuts, phase transitions).
  * ``export`` / ``report`` — Chrome-trace JSON for Perfetto, metrics
    JSON lines for scraping, and the ``tts report`` summarizer (steal
    efficiency, idle fraction per worker, cycle-rate timeline).

Closed-loop legs (same doc):

  * ``flightrec`` — crash-safe flight recorder: snapshot ring +
    last-dispatch registry, dumped as a valid trace on SIGTERM/SIGALRM/
    exception/watchdog stall (``TTS_FLIGHTREC``).
  * ``live`` — ``--obs-serve`` localhost HTTP/SSE snapshot streaming and
    the ``tts watch`` client.
  * ``costmodel`` — measured per-link latency+bandwidth profiles
    (``COSTMODEL.json``) that AdaptiveK and the mesh/dist periods resolve
    from (``TTS_COSTMODEL``).
  * ``phases`` — on-device per-phase cycle clocks (``TTS_PHASEPROF=1`` /
    ``tts profile``): a barrier-fenced clock block in the resident loop
    carry decomposing the chunk cycle into pop/eval/compact/push/
    overflow (+ mesh balance), plus the steady-state XLA trace window
    (``TTS_XLA_TRACE``). A separate cache-keyed program variant — never
    the headline program.

Knobs: ``TTS_OBS=1`` (everything), ``TTS_OBS=host`` (host events only —
device programs untouched), off by default with zero hot-loop cost.
``--trace out.json`` / ``--metrics-file m.jsonl`` on every CLI tier.
"""

from __future__ import annotations

import os
from contextlib import contextmanager

from . import costmodel, counters, events, export, flightrec, live, phases, report

__all__ = [
    "capture",
    "costmodel",
    "counters",
    "events",
    "export",
    "flightrec",
    "live",
    "obs_enabled",
    "phases",
    "report",
]


def obs_enabled() -> bool:
    return events.enabled()


class Capture:
    """Result handle of a ``capture()`` block."""

    def __init__(self):
        self.events: list[dict] = []

    def explored_totals(self) -> tuple[int, int]:
        """(tree, sol) summed over the engines' per-phase ``explored``
        counter samples — the obs-side mirror of
        ``SearchResult.explored_tree/explored_sol`` (tests pin exact
        parity)."""
        tree = sol = 0
        for e in self.events:
            if e.get("name") == "explored":
                a = e.get("args") or {}
                tree += a.get("tree", 0)
                sol += a.get("sol", 0)
        return tree, sol

    def summary(self) -> dict:
        return report.summarize(self.events)


@contextmanager
def capture(trace_path: str | None = None, metrics_path: str | None = None,
            mode: str = "1"):
    """Run-scoped telemetry capture: pins ``TTS_OBS`` to ``mode``
    (``"1"`` full / ``"host"`` events-only), clears the recorder, and on
    exit drains the events into the yielded ``Capture`` (optionally
    writing the trace / metrics files). Restores the previous ``TTS_OBS``
    so a caller's explicit setting is never clobbered.

    Device-counter note: ``mode="1"`` takes effect for programs *built*
    inside the block — the engines key their program caches on the obs
    state, so a cached obs-off program is rebuilt, not reused stale.
    """
    prev = os.environ.get("TTS_OBS")
    os.environ["TTS_OBS"] = mode
    events.reset()
    cap = Capture()
    try:
        yield cap
    finally:
        cap.events = events.drain()
        if prev is None:
            os.environ.pop("TTS_OBS", None)
        else:
            os.environ["TTS_OBS"] = prev
        if trace_path is not None:
            export.write_chrome_trace(cap.events, trace_path)
        if metrics_path is not None:
            export.write_metrics_jsonl(cap.events, metrics_path)
