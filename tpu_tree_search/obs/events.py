"""Host-side structured event tracing (``TTS_OBS``).

The reference kit's only observability is the final banner plus an appended
``stats_*.dat`` line (`pfsp_gpu_cuda.c:140-148`); the dynamics both
load-balancing papers diagnose from — steal rounds, idle windows, per-worker
imbalance (Helbecque et al., arXiv:2012.09511 §5; Melab et al.,
arXiv:0809.3285 §4) — are invisible. This module records them: a process-wide
recorder of timestamped structured events that the runtimes emit at their
natural host-side boundaries (dispatches, steals, exchange rounds, incumbent
improvements, phase transitions, checkpoint cuts).

Concurrency model: **thread-local append buffers, merged at drain**. Workers
(the multi/dist tiers run one host thread per device plus communicator
threads) append to their own bounded deque without taking any lock; the
recorder's lock guards only the buffer *registry* (taken once per thread,
at first emit) and the drain-time merge. No hot-path contention, no
cross-thread ordering requirement — events carry monotonic timestamps
(``time.perf_counter_ns``) and the merge sorts.

Cost model: every emit is gated on ``enabled()`` — one global read — so the
disabled path is a few nanoseconds per call site. Call sites are host-side
control points (per dispatch / steal / round), never per node or per cycle;
the on-device hot loop is covered by ``counters`` instead.

Event shape (Chrome-trace-event aligned, so export is a dump not a
translation): ``ph`` is the Chrome phase — ``"i"`` instant, ``"X"`` complete
(with ``dur``), ``"C"`` counter — ``ts``/``dur`` are microseconds, ``pid``
is the host id, ``tid`` the worker/communicator track.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque

#: Per-thread buffer bound: a runaway run (TTS_OBS=1 with nobody draining)
#: keeps the newest events instead of growing without bound.
MAX_EVENTS_PER_THREAD = 200_000

#: tid used for communicator/coordinator tracks (clear of worker ids).
COMM_TID = 1000

# -- job correlation (serve) -----------------------------------------------
# The serve scheduler runs many jobs through the same worker thread; a
# merged trace over a daemon's state-dir is useless if every span is
# anonymous. The scheduler binds the active job id per thread
# (``with job_context(job_id):`` around each slice); ``emit`` stamps it
# onto every event as a top-level ``"job"`` field, which the Chrome-trace
# export (obs/export.py) turns into per-job lanes and ``tts report``
# groups into per-job sections. Chrome/Perfetto ignore unknown fields,
# so stamped traces stay loadable everywhere.

_JOB_CTX = threading.local()


def current_job() -> str | None:
    """The job id bound to this thread, if any."""
    return getattr(_JOB_CTX, "job", None)


class job_context:
    """``with job_context("job-000001"):`` — stamp every event this
    thread emits with the job id. Nests (restores the previous binding);
    ``None`` is a no-op binding."""

    def __init__(self, job: str | None):
        self._job = job
        self._prev = None

    def __enter__(self):
        self._prev = getattr(_JOB_CTX, "job", None)
        _JOB_CTX.job = self._job
        return self

    def __exit__(self, *exc):
        _JOB_CTX.job = self._prev
        return False


def obs_mode() -> str:
    """The ``TTS_OBS`` knob: ``"0"``/unset = off, ``"1"`` = full (host
    events + on-device counters), ``"host"`` = host events only — the
    device programs stay byte-identical to obs-off, so a run can be traced
    without recompiling its resident step (bench uses this to attach the
    headline trace without perturbing the measurement)."""
    return os.environ.get("TTS_OBS", "0") or "0"


def enabled() -> bool:
    """Host event tracing on? (Any non-off mode.)"""
    return obs_mode() not in ("0",)


def now_us() -> float:
    """Monotonic microseconds — the trace time base."""
    return time.perf_counter_ns() / 1e3


class EventRecorder:
    """Thread-local buffers + locked registry; see module docstring."""

    def __init__(self, max_per_thread: int = MAX_EVENTS_PER_THREAD):
        self._local = threading.local()
        self._lock = threading.Lock()
        self._buffers: list[deque] = []  # guarded-by: _lock
        self._max = max_per_thread

    def _buf(self) -> deque:
        buf = getattr(self._local, "buf", None)
        if buf is None:
            buf = deque(maxlen=self._max)
            self._local.buf = buf
            with self._lock:
                self._buffers.append(buf)
        return buf

    def emit(self, event: dict) -> None:
        self._buf().append(event)

    def drain(self, timeout: float | None = None) -> list[dict]:
        """Merged, time-sorted snapshot of every thread's buffer.

        ``timeout`` bounds the registry-lock wait (the flight recorder
        drains from signal handlers and its watchdog thread — the
        interrupted thread could hold the lock mid-registration); on a
        timeout the merge proceeds best-effort without the lock (deque
        iteration is safe against concurrent appends; at worst a buffer
        registered this instant is missed)."""
        locked = (
            self._lock.acquire() if timeout is None
            else self._lock.acquire(timeout=timeout)
        )
        try:
            # tts-lint: waive guarded-by -- lock-timeout fallback for signal-handler drains: deque iteration over a list() copy is safe vs concurrent appends; a just-registered buffer may be missed
            merged = [e for buf in list(self._buffers) for e in list(buf)]
        finally:
            if locked:
                self._lock.release()
        merged.sort(key=lambda e: e.get("ts", 0.0))
        return merged

    def clear(self) -> None:
        with self._lock:
            for buf in self._buffers:
                buf.clear()


_recorder = EventRecorder()


def recorder() -> EventRecorder:
    return _recorder


def reset() -> None:
    """Empty every buffer (run-scoped captures call this on entry so one
    process's earlier runs don't leak into a new trace)."""
    _recorder.clear()


def drain(timeout: float | None = None) -> list[dict]:
    return _recorder.drain(timeout=timeout)


def emit(name: str, cat: str = "tts", ph: str = "i", wid: int = 0,
         host: int = 0, ts: float | None = None, dur: float | None = None,
         args: dict | None = None) -> None:
    """Record one event iff tracing is enabled (cheap no-op otherwise)."""
    if not enabled():
        return
    ev: dict = {
        "name": name,
        "cat": cat,
        "ph": ph,
        "ts": now_us() if ts is None else ts,
        "pid": host,
        "tid": wid,
    }
    if dur is not None:
        ev["dur"] = dur
    if args:
        ev["args"] = args
    job = getattr(_JOB_CTX, "job", None)
    if job is not None:
        ev["job"] = job
    _recorder.emit(ev)


def complete(name: str, start_us: float, cat: str = "tts", wid: int = 0,
             host: int = 0, args: dict | None = None) -> None:
    """A Chrome ``"X"`` complete event spanning ``start_us`` .. now."""
    if not enabled():
        return
    emit(name, cat=cat, ph="X", wid=wid, host=host, ts=start_us,
         dur=max(0.0, now_us() - start_us), args=args)


def counter(name: str, wid: int = 0, host: int = 0, **values) -> None:
    """A Chrome ``"C"`` counter sample (one Perfetto counter track per
    name); values must be numbers."""
    if not enabled():
        return
    emit(name, cat="metrics", ph="C", wid=wid, host=host, args=values)


class span:
    """``with span("steal", wid=3):`` — emits one complete event covering
    the block. Usable when tracing is off (no-op)."""

    def __init__(self, name: str, cat: str = "tts", wid: int = 0,
                 host: int = 0, args: dict | None = None):
        self.name = name
        self.cat = cat
        self.wid = wid
        self.host = host
        self.args = args
        self._t0 = 0.0

    def __enter__(self):
        self._t0 = now_us()
        return self

    def __exit__(self, *exc):
        complete(self.name, self._t0, cat=self.cat, wid=self.wid,
                 host=self.host, args=self.args)
        return False
