"""Trace and metrics exporters.

Two artifact formats, both written from the same drained event list:

  * **Chrome trace event JSON** (``write_chrome_trace``) — the
    ``{"traceEvents": [...]}`` object format, loadable in Perfetto
    (ui.perfetto.dev) or ``chrome://tracing``. One process row per host,
    one thread track per worker (plus a communicator track), counter
    events as counter tracks. Events are already recorded in this shape
    (``events.py``), so export is metadata + dump, not translation.
  * **metrics JSON lines** (``write_metrics_jsonl``) — one flat JSON
    object per counter sample (``ph == "C"``), suitable for scraping /
    `jq` / pandas; the machine-readable companion of the reference's
    appended ``stats_*.dat`` lines (`pfsp_gpu_cuda.c:140-148`).
"""

from __future__ import annotations

import json

from .events import COMM_TID


def _track_name(tid: int) -> str:
    if tid == COMM_TID:
        return "communicator"
    return f"worker{tid}"


#: tid base for synthetic per-job lanes (clear of worker ids and the
#: communicator track).
JOB_TID_BASE = 2000


def _job_lanes(evts: list[dict]) -> tuple[list[dict], dict]:
    """Remap job-stamped events (``events.job_context``, stamped by the
    serve scheduler) onto one synthetic thread lane per job, so a merged
    daemon trace renders per-job rows instead of interleaving every
    tenant's spans on one worker track. Events without a ``job`` field
    pass through untouched; lane ids are stable (sorted job order)."""
    jobs = sorted({e["job"] for e in evts
                   if isinstance(e, dict) and e.get("job") is not None})
    if not jobs:
        return evts, {}
    lane = {j: JOB_TID_BASE + i for i, j in enumerate(jobs)}
    out = []
    for e in evts:
        j = e.get("job") if isinstance(e, dict) else None
        if j is not None:
            e = {**e, "tid": lane[j]}
        out.append(e)
    return out, {lane[j]: j for j in jobs}


def chrome_trace_object(evts: list[dict], label: str = "tts") -> dict:
    """The full Chrome-trace object for a drained event list (metadata
    process/thread-name records prepended for every (pid, tid) seen)."""
    evts, job_lanes = _job_lanes(evts)
    meta: list[dict] = []
    pids = sorted({e.get("pid", 0) for e in evts})
    tracks = sorted({(e.get("pid", 0), e.get("tid", 0)) for e in evts})
    for pid in pids:
        meta.append({
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": f"{label} host{pid}"},
        })
    for pid, tid in tracks:
        meta.append({
            "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
            "args": {"name": job_lanes.get(tid) or _track_name(tid)},
        })
    other = {"producer": "tpu_tree_search obs"}
    # Dispatch-pipeline metadata (docs/OBSERVABILITY.md span semantics):
    # the resident engines emit one "pipeline" instant at phase-2 start;
    # a reader needs the depth to interpret overlapping dispatch spans.
    pipe = next(
        (e.get("args") or {} for e in evts if e.get("name") == "pipeline"),
        None,
    )
    if pipe is not None:
        other["pipeline_depth"] = pipe.get("depth", 1)
        if "K" in pipe:
            other["k_initial"] = pipe["K"]
        if "k_auto" in pipe:
            other["k_auto"] = pipe["k_auto"]
    return {
        "traceEvents": meta + evts,
        "displayTimeUnit": "ms",
        "otherData": other,
    }


def _fsync(f) -> None:
    """Flush + fsync (durability satellite: the tail of a killed run must
    survive — an OS-buffered write dies with the process)."""
    f.flush()
    try:
        import os

        os.fsync(f.fileno())
    except OSError:
        pass  # exotic filesystems; the flush already left the process


def write_chrome_trace(evts: list[dict], path: str, label: str = "tts") -> int:
    """Write the trace file (fsync'd); returns the event count (sans
    metadata)."""
    with open(path, "w") as f:
        json.dump(chrome_trace_object(evts, label=label), f)
        _fsync(f)
    return len(evts)


def load_trace(path: str) -> list[dict]:
    """Read back a trace file (either the object format this module writes
    or a bare event array) minus metadata records."""
    with open(path) as f:
        obj = json.load(f)
    evts = obj["traceEvents"] if isinstance(obj, dict) else obj
    return [e for e in evts if e.get("ph") != "M"]


def _metrics_line_event(rec: dict) -> dict:
    """A metrics-JSONL record back into counter-event shape, so the report
    summarizer consumes traces and metrics files interchangeably."""
    args = {k: v for k, v in rec.items()
            if k not in ("ts_us", "name", "host", "worker")}
    return {
        "name": rec.get("name", ""), "cat": "metrics", "ph": "C",
        "ts": rec.get("ts_us", 0.0), "pid": rec.get("host", 0),
        "tid": rec.get("worker", 0), "args": args,
    }


def _salvage_truncated(text: str) -> list[dict]:
    """Best-effort event recovery from a truncated trace: a killed writer
    leaves a prefix of the ``{"traceEvents": [...`` object — walk the
    array with ``raw_decode`` and keep every complete event object."""
    start = text.find("[")
    if start < 0:
        return []
    dec = json.JSONDecoder()
    evts: list[dict] = []
    i = start + 1
    n = len(text)
    while i < n:
        while i < n and text[i] in " \t\r\n,":
            i += 1
        if i >= n or text[i] != "{":
            break
        try:
            obj, end = dec.raw_decode(text, i)
        except ValueError:
            break
        if isinstance(obj, dict):
            evts.append(obj)
        i = end
    return evts


def load_trace_lenient(path: str) -> tuple[list[dict], str | None]:
    """Load a trace, a metrics JSONL, or the readable prefix of either —
    the ``tts report`` robustness contract: report what exists. Returns
    ``(events, warning)``; raises ``OSError`` only when the file cannot
    be read at all."""
    with open(path) as f:
        text = f.read()
    if not text.strip():
        return [], f"{path}: empty file"
    try:
        obj = json.loads(text)
    except ValueError:
        obj = None
    if isinstance(obj, dict) and isinstance(obj.get("traceEvents"), list):
        return ([e for e in obj["traceEvents"] if isinstance(e, dict)
                 and e.get("ph") != "M"], None)
    if isinstance(obj, list):
        return ([e for e in obj if isinstance(e, dict)
                 and e.get("ph") != "M"], None)
    # Not one whole JSON document: metrics JSONL, or a truncated trace.
    lines = text.splitlines()
    recs = []
    for ln in lines:
        ln = ln.strip()
        if not ln:
            continue
        try:
            rec = json.loads(ln)
        except ValueError:
            continue  # torn tail line from a mid-write kill
        if isinstance(rec, dict):
            recs.append(rec)
    if recs:
        if "ph" in recs[0]:  # a JSONL of raw events
            return ([e for e in recs if e.get("ph") != "M"],
                    f"{path}: read as event JSONL ({len(recs)} lines)")
        return ([_metrics_line_event(r) for r in recs],
                f"{path}: read as metrics JSONL ({len(recs)} lines)")
    evts = [e for e in _salvage_truncated(text) if e.get("ph") != "M"]
    if evts:
        return evts, f"{path}: truncated trace, salvaged {len(evts)} events"
    return [], f"{path}: unrecognized/corrupt content, no events recovered"


def metrics_lines(evts: list[dict]) -> list[dict]:
    """Flatten counter samples to scrape-ready records."""
    out = []
    for e in evts:
        if e.get("ph") != "C":
            continue
        rec = {
            "ts_us": e.get("ts", 0.0),
            "name": e.get("name", ""),
            "host": e.get("pid", 0),
            "worker": e.get("tid", 0),
        }
        rec.update(e.get("args") or {})
        out.append(rec)
    return out


def write_metrics_jsonl(evts: list[dict], path: str) -> int:
    """Append one JSON line per counter sample; returns the line count.
    Append mode on purpose — like the reference's ``--stats-file``, repeat
    runs accumulate into one scrapeable file."""
    lines = metrics_lines(evts)
    with open(path, "a") as f:
        for rec in lines:
            f.write(json.dumps(rec) + "\n")
        _fsync(f)
    return len(lines)
