"""Measured cost-model profiles (``COSTMODEL.json``; docs/OBSERVABILITY.md).

The controllers that pace every host loop — AdaptiveK's target host-period
band (engine/pipeline.py) and, through it, the mesh/dist tiers' steal and
exchange cadence (their diffusion/exchange rounds ride dispatch
boundaries) — were tuned against a *fixed* 100-250 ms band, an assumption
about the host<->device round trip, not a measurement. This module closes
the loop the way arXiv:1904.06825 prescribes: fit a **latency + bandwidth
model per link class** from the spans the obs layer already records, and
let the controllers resolve their bands from the measured fit when a
profile exists (the fixed bands remain the documented fallback).

Link classes and their span sources (all host-side; nothing new runs on
device):

  * ``dispatch`` — resident/mesh/dist_mesh ``dispatch`` spans: duration
    vs device cycles. The intercept IS the per-dispatch host round trip
    (H2D command + D2H scalar read, ~360 ms through a tunnel), the slope
    the per-cycle device time.
  * ``offload``  — multi/dist worker ``chunk`` spans: duration vs chunk
    node count (H2D staging + kernel + D2H collect per chunk).
  * ``exchange`` — dist/dist_mesh communicator ``exchange`` spans:
    the inter-host control-round (allgather over DCN/KV) latency.
  * ``donate``   — ``donate_send``/``donate_recv`` spans: duration vs
    payload bytes — the DCN/KV work-migration bandwidth. Spans stamped
    with a link class (parallel/topology.py) also bucket per class
    (``donate:ici`` / ``donate:dcn``) — the fits the hierarchical steal
    policy resolves its per-level quanta and periods from
    (``steal_quantum`` / ``steal_every``).
  * ``steal``    — intra-host worker ``steal`` spans: locked front-pop
    + push duration vs stolen node count (the ``local`` link class).

A profile entry is keyed by ``backend|topology|shape`` (e.g.
``tpu|device-D1|pfsp_j20x10_lb1``) so a ta014 fit never paces an N-Queens
run on another topology; lookup degrades gracefully (same backend+shape on
any topology, then same backend) because the *dispatch intercept* — the
quantity the bands derive from — is a property of the host link, not the
problem.

Band derivation (``resolve_band``): the fixed defaults encode an assumed
8 ms round trip — ``RESIDENT_TARGET`` (0.100, 0.250) is 12.5x/31.25x that
latency; ``MESH_TARGET`` (0.050, 0.150) is 6.25x/18.75x. A measured
latency L replaces the assumption with the same multipliers, clamped so a
pathological fit cannot park K at a useless rung. Deterministic given the
profile, and bit-identical search results by construction — the band only
moves K along the existing ladder (tests/test_costmodel.py pins both).
"""

from __future__ import annotations

import json
import os

#: (lo_multiplier, hi_over_lo, lo_clamp, hi_clamp) per controller tier —
#: chosen so the measured-band formula reproduces the documented fixed
#: bands exactly at the 8 ms design-point latency (see module docstring).
_BAND_RULES = {
    "resident": (12.5, 2.5, (0.020, 2.0), 5.0),
    "mesh": (6.25, 3.0, (0.010, 1.0), 3.0),
}

#: Span name -> (link class, x-axis arg). ``None`` x means latency-only.
_SPAN_LINKS = {
    "dispatch": ("dispatch", "cycles"),
    "chunk": ("offload", "count"),
    "exchange": ("exchange", None),
    "donate_send": ("donate", "bytes"),
    "donate_recv": ("donate", "bytes"),
    "steal": ("steal", "nodes"),
}

_X_UNITS = {"dispatch": "cycle", "offload": "node", "exchange": None,
            "donate": "byte", "steal": "node"}

#: Link classes a donate span may be stamped with (``args["link"]``,
#: parallel/topology.py): stamped spans ALSO bucket into the per-class
#: ``donate:ici`` / ``donate:dcn`` fits the hierarchical steal policy
#: resolves its per-level quanta and periods from.
_DONATE_CLASSES = ("ici", "dcn")

#: Target amortization: a donation's transfer cost must stay below this
#: fraction of the evaluation time the block buys (steal_quantum).
DONATE_FRAC = 0.10


def costmodel_path() -> str | None:
    """The ``TTS_COSTMODEL`` knob: a profile path arms measured bands;
    unset/``0`` keeps the fixed fallbacks."""
    raw = os.environ.get("TTS_COSTMODEL", "") or ""
    return None if raw in ("", "0") else raw


def shape_class(problem) -> str:
    """Problem shape class for profile keys: bound work scales with the
    (jobs, machines)/(N) shape and the bound function, nothing finer."""
    if problem is None:
        return "any"
    if hasattr(problem, "N"):
        return f"nqueens_n{problem.N}"
    if hasattr(problem, "jobs"):
        lb = getattr(problem, "lb", "lb1")
        return f"pfsp_j{problem.jobs}x{problem.machines}_{lb}"
    return getattr(problem, "name", type(problem).__name__).lower()


def profile_key(backend: str, topology: str, shape: str) -> str:
    return f"{backend}|{topology}|{shape}"


def _percentile(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = q * (len(sorted_vals) - 1)
    lo = int(idx)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = idx - lo
    return sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac


def fit_link(samples: list[tuple[float, float]]) -> dict | None:
    """Least-squares latency+bandwidth fit of ``(x, duration_us)`` span
    samples: ``dur = latency_us + x * per_unit_us``. With too few samples
    (or no x spread) the latency falls back to the median duration and the
    slope is None. Percentiles always report the raw durations."""
    if not samples:
        return None
    durs = sorted(d for _, d in samples)
    n = len(samples)
    med = _percentile(durs, 0.5)
    out = {
        "n": n,
        "p50_us": round(med, 1),
        "p90_us": round(_percentile(durs, 0.90), 1),
        "p99_us": round(_percentile(durs, 0.99), 1),
        "latency_us": round(med, 1),
        "per_unit_us": None,
    }
    # Trim the slowest ~10% before the linear fit: the first dispatches of
    # a run carry compilation (observed: a 760 ms compile spike vs ~10 ms
    # steady state), and a least-squares intercept is exactly what such
    # outliers wreck. Percentiles above stay untrimmed on purpose — p99
    # SHOULD show the spike.
    fit_samples = samples
    if n >= 8:
        cut = _percentile(durs, 0.90)
        trimmed = [(x, d) for x, d in samples if d <= cut]
        if len(trimmed) >= 3:
            fit_samples = trimmed
    xs = [x for x, _ in fit_samples]
    nf = len(fit_samples)
    mean_x = sum(xs) / nf
    var_x = sum((x - mean_x) ** 2 for x in xs)
    if nf >= 3 and var_x > 0.0:
        mean_d = sum(d for _, d in fit_samples) / nf
        cov = sum((x - mean_x) * (d - mean_d) for x, d in fit_samples)
        slope = max(0.0, cov / var_x)
        intercept = max(0.0, mean_d - slope * mean_x)
        out["latency_us"] = round(intercept, 1)
        out["per_unit_us"] = round(slope, 4)
        if slope > 0:
            out["per_sec"] = round(1e6 / slope, 1)  # cycles/nodes/bytes per s
    return out


def samples_from_events(evts: list[dict]) -> dict[str, list]:
    """Bucket every recognized complete span into its link class as
    ``(x, dur_us)`` samples (events without ``dur`` are skipped — the
    older instant spellings of exchange/donate carry no timing)."""
    links: dict[str, list] = {}
    for e in evts:
        if e.get("ph") != "X" or "dur" not in e:
            continue
        hit = _SPAN_LINKS.get(e.get("name", ""))
        if hit is None:
            continue
        link, xarg = hit
        args = e.get("args") or {}
        if xarg is None:
            x = 0.0
        else:
            x = args.get(xarg)
            if x is None and link == "donate":
                x = args.get("nodes")  # older traces: nodes, not bytes
            if x is None:
                continue
        links.setdefault(link, []).append((float(x), float(e["dur"])))
        # Link-class-stamped donations additionally feed the per-class
        # fits (donate:ici / donate:dcn) the steal hierarchy sizes its
        # per-level quanta from; the aggregate "donate" bucket stays for
        # older consumers.
        if link == "donate" and args.get("link") in _DONATE_CLASSES:
            links.setdefault(f"donate:{args['link']}", []).append(
                (float(x), float(e["dur"]))
            )
    return links


def build_profile(evts: list[dict], backend: str, topology: str,
                  shape: str) -> dict:
    """One profile entry (keyed) from a drained/loaded event list."""
    links = {
        name: fit
        for name, samples in sorted(samples_from_events(evts).items())
        if (fit := fit_link(samples)) is not None
    }
    return {
        profile_key(backend, topology, shape): {
            "backend": backend,
            "topology": topology,
            "shape": shape,
            "links": links,
        }
    }


def save(path: str, profile: dict) -> dict:
    """Merge ``profile`` into the file at ``path`` (atomic replace +
    fsync — a capture must survive the session dying right after it).
    Returns the merged document."""
    merged = load(path) or {}
    merged.update(profile)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(merged, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return merged


def load(path: str) -> dict | None:
    """Load a profile document; None on any failure (the controllers fall
    back to their fixed bands — a corrupt profile must never fail a run)."""
    try:
        with open(path) as f:
            doc = json.load(f)
        return doc if isinstance(doc, dict) else None
    except (OSError, ValueError):
        return None


def lookup(profile: dict, backend: str, topology: str, shape: str
           ) -> tuple[str, dict] | None:
    """Best matching entry: exact key, then same backend+shape on any
    topology, then same backend — sorted for determinism. The degradation
    order follows what the bands actually consume (the dispatch intercept
    is a link property; see module docstring)."""
    exact = profile_key(backend, topology, shape)
    if isinstance(profile.get(exact), dict):
        return exact, profile[exact]
    candidates = sorted(
        k for k, v in profile.items()
        if isinstance(v, dict) and v.get("backend") == backend
    )
    for k in candidates:
        if profile[k].get("shape") == shape:
            return k, profile[k]
    if candidates:
        return candidates[0], profile[candidates[0]]
    return None


def resolve_band(entry: dict, tier: str) -> tuple[float, float] | None:
    """AdaptiveK target band (seconds) from a profile entry's measured
    dispatch latency; None when the entry carries no usable dispatch fit
    (callers keep their fixed band)."""
    rule = _BAND_RULES.get("mesh" if tier in ("mesh", "dist_mesh")
                           else "resident")
    disp = (entry.get("links") or {}).get("dispatch") or {}
    lat_us = disp.get("latency_us")
    if not lat_us or lat_us <= 0:
        return None
    lo_mult, hi_over_lo, (lo_min, lo_max), hi_cap = rule
    lo = min(max(lo_mult * lat_us / 1e6, lo_min), lo_max)
    hi = min(hi_over_lo * lo, hi_cap)
    return (round(lo, 4), round(hi, 4))


def exchange_sleep_s(entry: dict, cap_s: float = 0.5) -> float | None:
    """Idle-host exchange back-off from the measured exchange-round
    latency (dist_mesh: an idle host that received nothing sleeps ~2
    round-trips instead of a fixed guess); None without an exchange fit."""
    exch = (entry.get("links") or {}).get("exchange") or {}
    p50 = exch.get("p50_us")
    if not p50 or p50 <= 0:
        return None
    return round(min(2.0 * p50 / 1e6, cap_s), 4)


def donate_fit(entry: dict, link: str) -> dict | None:
    """The donate fit for one link class: the stamped per-class fit
    (``donate:ici`` / ``donate:dcn``) when the profile carries one, else
    the aggregate ``donate`` fit (older profiles, single-class runs)."""
    links = entry.get("links") or {}
    fit = links.get(f"donate:{link}") or links.get("donate")
    return fit if isinstance(fit, dict) else None


def steal_quantum(entry: dict, link: str, *, m: int,
                  bytes_per_node: int | None, cap: int,
                  frac: float = DONATE_FRAC) -> int | None:
    """Donation quantum (nodes) for ``link`` sized so the measured
    transfer cost amortizes below ``frac`` of the evaluation time the
    block buys:

        lat_us + Q*bpn*per_byte_us  <=  frac * Q*eval_per_node_us
        =>  Q >= lat_us / (frac*eval_per_node_us - bpn*per_byte_us)

    ``eval_per_node_us`` is the offload (chunk) fit's slope — the
    measured per-node evaluation cost on this backend. When the per-byte
    transfer cost alone exceeds the amortization budget no finite quantum
    qualifies; go maximally bulk (``cap``) to pay the latency as rarely
    as possible. None (caller keeps the fixed fallback) without both a
    donate-latency and an eval-rate fit. Clamped to [2m, cap] — a block
    below 2m could not have been popped anyway (pop_front_bulk_half's
    donor threshold)."""
    fit = donate_fit(entry, link)
    off = (entry.get("links") or {}).get("offload") or {}
    eval_us = off.get("per_unit_us")
    lat_us = (fit or {}).get("latency_us")
    if not fit or not eval_us or eval_us <= 0 or not lat_us or lat_us <= 0:
        return None
    per_byte_us = fit.get("per_unit_us") or 0.0
    xfer_per_node_us = (bytes_per_node or 0) * per_byte_us
    denom = frac * eval_us - xfer_per_node_us
    if denom <= 0:
        return int(cap)
    q = lat_us / denom
    return int(min(max(q, 2 * m), cap))


def steal_every(entry: dict, interval_s: float, *, cap: int = 32,
                frac: float = DONATE_FRAC) -> int | None:
    """Far-level period, in near-round multiples: far (dcn) pairs match
    every ``N``-th exchange round where N spaces donations ~one latency
    per ``1/frac`` latencies of elapsed time — the same amortization
    target as the quantum, applied to the round cadence. None without a
    donate-latency fit for the far link."""
    fit = donate_fit(entry, "dcn")
    lat_us = (fit or {}).get("latency_us")
    if not fit or not lat_us or lat_us <= 0 or interval_s <= 0:
        return None
    n = (lat_us / 1e6) / (frac * interval_s)
    return int(min(max(round(n), 2), cap))
