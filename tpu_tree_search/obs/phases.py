"""On-device per-phase cycle-clock block (``TTS_PHASEPROF=1``).

Leg 1 (``counters.py``) counts WORK per dispatch; this leg measures TIME
per phase *inside* the resident ``lax.while_loop`` — which of
pop / bound-evaluation / compaction / fused-push / overflow-fallback /
mesh-balance actually dominates a chunk cycle.  That decomposition is the
gate on ROADMAP item 3 (the one-kernel resident cycle): `bench.py`'s
``eval_cycle_ms`` subtraction prices the evaluator against everything
else at dispatch granularity, but cannot split the remaining ~85% of the
cycle into its phases (BASELINE r5).  The measured per-link performance
models this repo leans on (arXiv:1904.06825; the PFSP scale-out study
arXiv:2012.09511) are built from exactly this kind of phase-attributed
timing.

Design — the counter-block pattern with a clock instead of an adder:

  * the loop carry gains one fixed-shape ``(NSLOTS + 1,)`` uint32 block:
    per-phase accumulated nanoseconds plus the last clock reading
    (``TPREV``), reset per dispatch and harvested only at the existing
    K-cycle dispatch boundaries (no new transfers; ``TTS_GUARD=1`` green);
  * each phase boundary routes the phase's outputs through
    ``lax.optimization_barrier`` together with the previous reading, then
    reads the clock with a data dependence on the barrier output — XLA
    cannot hoist the read before the phase or sink the phase past it
    (caveats below);
  * phase deltas telescope: within a cycle the same readings bound
    adjacent phases, so ``pop + eval + compact + push + overflow ==
    total`` holds EXACTLY on the harvested block (tests pin it) — the
    unattributed remainder (while-loop cond, carry plumbing, inter-round
    gaps) lands in ``loop``/``balance``, outside ``total``.

Clock source: jax exposes no portable on-device cycle-counter primitive
(this jaxlib's Mosaic TPU dialect has no timestamp op either), so the
clock is a ``jax.pure_callback`` reading ``time.perf_counter_ns()`` on
the host — truncated to uint32 so deltas wrap correctly (one phase
segment must stay under ~4.29 s; the K clamp keeps dispatches far under
that on every measured config).  On CPU the callback is nanoseconds-cheap;
on TPU each read is a host round trip, which is exactly why the armed
program is a **separate cache-keyed variant** (``TTS_PHASEPROF`` rides
the program caches next to ``TTS_OBS``): it is a profiling build for
`tts profile`, never the headline-measurement program.  When a device
cycle-counter op lands in jax, ``read_clock`` is the single seam to swap.

Barrier-placement caveats (docs/OBSERVABILITY.md leg 7): the barrier
fences only the values passed through it, so ops that feed nothing at the
next boundary can still be scheduled across it; XLA may also fuse less
across barriers, perturbing the very schedule being measured.  Phase
shares are therefore attribution estimates; the telescoped ``total`` and
the armed-vs-off bit-identity of search results are the hard guarantees.

Zero-cost disabled path: enablement is decided at program build time
(``phase_profiling_enabled()``); when off, carry/body/jaxpr are
byte-identical to a build without this module (tests/test_phases.py).
"""

from __future__ import annotations

import functools
import os
import threading
import time

import numpy as np

#: Phase slots. The first five partition the chunk cycle exactly
#: (``total`` is their telescoped sum); ``balance`` (mesh diffusion +
#: incumbent fold, per round) and ``loop`` (while-cond + carry plumbing
#: between cycles) sit outside the cycle.
SLOTS = (
    "pop",       # chunk pop/select: dynamic_slice of the pool back
    "eval",      # bound evaluation (lb1/lb2/N-Queens labels)
    "compact",   # survivor ranks + rank inversion (ops/compaction.py)
    "push",      # fused prune+push fast path (fits == True cycles)
    "overflow",  # overflow-branch push (fits == False cycles)
    "balance",   # mesh tiers: pmin fold + ppermute diffusion, per round
    "loop",      # inter-cycle remainder: cond, carry, loop entry/exit
    "total",     # per-cycle end - start (== pop+eval+compact+push+overflow)
)
NSLOTS = len(SLOTS)

#: SLOTS index lookup, e.g. ``IDX["compact"]``.
IDX = {name: i for i, name in enumerate(SLOTS)}

#: Block index of the carried last clock reading (not a phase slot).
TPREV = NSLOTS

#: The slots that partition the chunk cycle (their sum == ``total``).
CYCLE_SLOTS = ("pop", "eval", "compact", "push", "overflow")


def phase_profiling_enabled() -> bool:
    """True only for ``TTS_PHASEPROF=1`` — the armed program variant."""
    return os.environ.get("TTS_PHASEPROF", "0") == "1"


def clock_source() -> str:
    """The active clock implementation. Only ``"callback"`` exists today
    (see module docstring); a future hardware cycle-counter op slots in
    here without touching any call site."""
    return "callback"


def _read_ns(tag, *deps):
    # Host side of the clock: deps are ignored (they exist to order the
    # read after the fenced phase and to defeat CSE between boundaries).
    return np.uint32(time.perf_counter_ns() & 0xFFFFFFFF)


# tts-lint: traced (called from the resident while-loop body when armed)
def read_clock(dep, tag: str):
    """One uint32 clock reading, data-dependent on ``dep``. ``tag`` is
    static and baked into the callback identity (a distinct partial per
    boundary), so XLA cannot dedup two boundaries into one read."""
    import jax
    import jax.numpy as jnp

    return jax.pure_callback(
        functools.partial(_read_ns, tag),
        jax.ShapeDtypeStruct((), jnp.uint32), dep,
    )


def init_block():
    """Fresh all-zeros phase block (``(NSLOTS + 1,)`` uint32)."""
    import jax.numpy as jnp

    return jnp.zeros((NSLOTS + 1,), jnp.uint32)


# tts-lint: traced (runs inside the jitted step, before the while loop)
def seed_block(dep=None):
    """A fresh block whose ``TPREV`` holds a pre-loop clock reading — the
    base of the first cycle's ``loop`` delta. ``dep`` (any traced value)
    orders the read after the dispatch's inputs are live."""
    import jax.numpy as jnp

    block = init_block()
    t0 = read_clock(jnp.uint32(0) if dep is None else dep, "seed")
    return block.at[TPREV].set(t0)


# tts-lint: traced (called from the resident while-loop body when armed)
def boundary(block, slot, *vals, tag: str | None = None):
    """Close one phase: fence ``vals`` (THE values the next phase
    consumes — pass them through and use the returned versions, or the
    barrier fences nothing), read the clock, charge ``now - TPREV`` to
    ``slot``, and advance ``TPREV``.

    ``slot`` is a static name or a traced int32 index (the push/overflow
    branch charges by predicate); a traced slot needs a static ``tag``
    for the callback identity. Returns ``(block, fenced_vals_tuple)``.
    """
    import jax.numpy as jnp
    from jax import lax

    fenced = lax.optimization_barrier((block[TPREV],) + tuple(vals))
    tprev, out = fenced[0], tuple(fenced[1:])
    t = read_clock(tprev, tag if tag is not None else slot)
    dt = t - tprev  # uint32 arithmetic: wrap-correct for segments < 2^32 ns
    idx = IDX[slot] if isinstance(slot, str) else slot
    block = block.at[idx].add(dt).at[TPREV].set(t)
    return block, out


# tts-lint: traced (called from the resident while-loop body when armed)
def close_total(block, t_start):
    """Charge the whole-cycle delta (last reading - ``t_start``, the
    reading ``boundary`` stored when the cycle began) to ``total``."""
    return block.at[IDX["total"]].add(block[TPREV] - t_start)


def merge_host(total: dict | None, block) -> dict:
    """Host-side accumulation of one harvested block (np array, possibly
    (D, NSLOTS+1) for the mesh tiers) into running per-phase nanosecond
    totals (Python ints — no wraparound across dispatches). Multi-shard
    blocks sum: the totals are aggregate device-time per phase, so the
    SHARES are D-invariant even though the sums exceed wall time."""
    arr = np.asarray(block, dtype=np.int64).reshape(-1, NSLOTS + 1)
    out = dict(total) if total else {name: 0 for name in SLOTS}
    for i, name in enumerate(SLOTS):
        out[name] = out.get(name, 0) + int(arr[:, i].sum())
    return out


def as_args(block) -> dict:
    """A harvested block as a {slot: ns} dict for counter events and
    metrics lines."""
    return merge_host(None, block)


def shares(totals: dict) -> dict:
    """Per-phase share of the measured cycle time: each CYCLE slot over
    ``total`` (0.0..1.0); ``balance``/``loop`` are reported relative to
    ``total`` too (they can exceed 1.0 — they are outside the cycle)."""
    t = max(1, int(totals.get("total", 0)))
    return {
        name: totals.get(name, 0) / t
        for name in SLOTS if name != "total"
    }


def dominant_phase(totals: dict | None) -> tuple[str, float] | None:
    """(name, share) of the largest in-cycle phase — the "next structural
    cost" line of ``tts report``/``tts profile``. None without data."""
    if not totals or not totals.get("total"):
        return None
    name = max(CYCLE_SLOTS, key=lambda s: totals.get(s, 0))
    return name, totals.get(name, 0) / max(1, int(totals["total"]))


def decomp(totals: dict) -> dict:
    """The decomposition record `tts report`/`tts profile` render:
    raw ns, cycle shares, and the dominant in-cycle phase."""
    dom = dominant_phase(totals)
    return {
        "ns": {k: int(v) for k, v in totals.items()},
        "shares": {k: round(v, 4) for k, v in shares(totals).items()},
        "dominant": dom[0] if dom else None,
        "dominant_share": round(dom[1], 4) if dom else None,
    }


# -- XLA profiler capture (`tts profile` / --xla-trace) ----------------------

#: Dispatch boundaries to skip before starting the XLA trace: the first
#: dispatch carries the while-loop compile, the second may still hit
#: autotuning caches — the window opens at steady state.
TRACE_SKIP_DISPATCHES = 1


def xla_trace_dir() -> str | None:
    """``TTS_XLA_TRACE=<dir>`` — arm a steady-state XLA profiler capture
    around the dispatch window (CLI: ``--xla-trace DIR``)."""
    return os.environ.get("TTS_XLA_TRACE") or None


class XlaTraceWindow:
    """Steady-state ``jax.profiler.start_trace``/``stop_trace`` bracket.

    The engines call ``on_dispatch(seq)`` once per consumed dispatch and
    ``close()`` when phase 2 ends: the trace opens after
    ``TRACE_SKIP_DISPATCHES`` completed dispatches (warmup + while-loop
    compile excluded) and closes before the residual download — so the
    capture is the steady-state dispatch window, not the session.  The
    jax profiler is process-global: only one window can be active (the
    dist_mesh virtual-host threads share one; extras are no-ops).
    """

    _active_lock = threading.Lock()
    _active: "XlaTraceWindow | None" = None

    def __init__(self, tier: str, out_dir: str | None = None):
        self.tier = tier
        self.dir = out_dir if out_dir is not None else xla_trace_dir()
        self.started = False
        self._owner = False
        if self.dir:
            with XlaTraceWindow._active_lock:
                if XlaTraceWindow._active is None:
                    XlaTraceWindow._active = self
                    self._owner = True

    def on_dispatch(self, seq: int) -> None:
        if (not self._owner or self.started
                or seq < TRACE_SKIP_DISPATCHES + 1):
            return
        import jax

        try:
            os.makedirs(self.dir, exist_ok=True)
            jax.profiler.start_trace(self.dir)
            self.started = True
            from . import events as ev

            ev.emit("xla_trace", args={"dir": self.dir, "tier": self.tier,
                                       "after_dispatch": seq - 1})
        except Exception:  # noqa: BLE001 — capture must never fail a run
            self._release()

    def close(self) -> None:
        if self.started:
            import jax

            try:
                jax.profiler.stop_trace()
            except Exception:  # noqa: BLE001 — see on_dispatch
                pass
            self.started = False
        self._release()

    def _release(self) -> None:
        if self._owner:
            with XlaTraceWindow._active_lock:
                if XlaTraceWindow._active is self:
                    XlaTraceWindow._active = None
            self._owner = False


# -- compiled-program contracts (`tts check`, analysis/contracts.py) --------

from ..analysis.contracts import contract


@contract(
    "phaseprof-off-identity",
    claim="TTS_PHASEPROF unset and =0 build byte-identical resident step "
          "jaxprs — the phase-clock block is compiled out when off, never "
          "branched (same contract as the obs counter block)",
    artifact="variants",
)
def _contract_phaseprof_off_identity(art, cell):
    if not art.has("off", "phase0"):
        return []
    out = []
    if art.text("off") != art.text("phase0"):
        out.append("TTS_PHASEPROF=0 build differs from the unset build "
                   "(clock reads leaked into the off path)")
    if art.outvars("phase0") != art.outvars("off"):
        out.append("TTS_PHASEPROF=0 build changed the carry width")
    return out


@contract(
    "phaseprof-block-leaf",
    claim="the armed phase profiler adds exactly ONE output leaf (the "
          "phase-clock block), two when device counters ride along "
          "(order: ..., ctr, ph) — and genuinely changes the program",
    artifact="variants",
)
def _contract_phaseprof_block(art, cell):
    if not art.has("off", "phase1", "phase1-obs1"):
        return []
    out = []
    base = art.outvars("off")
    if art.outvars("phase1") != base + 1:
        out.append(
            f"armed phase build carries {art.outvars('phase1')} output "
            f"leaves (expected {base + 1})"
        )
    if art.outvars("phase1-obs1") != base + 2:
        out.append(
            f"armed phase+obs build carries {art.outvars('phase1-obs1')} "
            f"output leaves (expected {base + 2})"
        )
    if art.text("phase1") == art.text("off"):
        out.append("armed phase build is byte-identical to off (the clock "
                   "block is silently gone)")
    return out
