"""On-device cycle counters for the resident engines (``TTS_OBS=1``).

The reference ships always-on diagnostics counters and per-run stats lines
(SURVEY.md §4, `pfsp_gpu_cuda.c:140-148`); the resident engines here run up
to K chunk cycles inside one jitted ``lax.while_loop``, so per-cycle
dynamics (pool occupancy, prune rates, overflow fallbacks) are invisible to
the host by design. This module adds a small **fixed-shape counter block**
to the loop carry — accumulated with pure jnp ops inside the traced body,
harvested only at the existing K-cycle dispatch boundaries where the host
already reads the tree/sol/cycles scalars. Steady state stays transfer-free
and recompile-free: the block rides the same dispatch result the engine
reads anyway, so ``TTS_GUARD=1`` sees nothing new.

Zero-cost disabled path: enablement is decided at **program build time**
(``device_counters_enabled()``, baked into the engines' program cache
keys). When off, the carry, the body, and the jaxpr are byte-identical to a
build without this module — counters are compiled out, not branched
(tests/test_obs.py pins this).

Slot semantics (``SLOTS`` order; all int32, reset each dispatch):

  * ``popped``      — parents popped (sum of per-cycle ``cnt``);
  * ``pushed``      — children pushed (== exploredTree increments);
  * ``leaves``      — solution leaves counted (== exploredSol increments);
  * ``pruned``      — candidate child slots not pushed and not leaves:
                      ``cnt * child_slots - pushed - leaves`` (includes the
                      structurally-closed slots of deep PFSP parents — the
                      bound-cut vs closed split is not observable from the
                      body without re-deriving the evaluator's masks);
  * ``overflow``    — cycles that took the overflow fallback (survivors
                      exceeded the compaction budget S);
  * ``pool_hwm``    — high-water mark of the pool size after the push;
  * ``surv_hwm``    — high-water mark of per-cycle survivors (``tree_inc``);
  * ``push_rows``   — rows the survivor-path push stage processed (the
                      fused path touches its full S budget per cycle, the
                      overflow path the whole M*n reservation).  Together
                      with the evaluator's child-eval count
                      (``pushed + leaves + pruned``) this is the
                      maintenance-vs-evaluator WORK split `tts report`
                      prints — a device-side clock does not exist, so the
                      time split is measured at dispatch level by
                      ``bench.py``'s eval-only-loop calibration instead.

Counter headroom rides the engines' existing K clamp (``K*M*n < 2^31`` per
dispatch); the host accumulates across dispatches in Python ints.
"""

from __future__ import annotations

import os

SLOTS = (
    "popped",
    "pushed",
    "leaves",
    "pruned",
    "overflow",
    "pool_hwm",
    "surv_hwm",
    "push_rows",
)
NSLOTS = len(SLOTS)

#: SLOTS index lookup, e.g. ``IDX["pushed"]``.
IDX = {name: i for i, name in enumerate(SLOTS)}

#: Slots accumulated as running maxima (the rest add).
_MAX_SLOTS = frozenset((IDX["pool_hwm"], IDX["surv_hwm"]))


def device_counters_enabled() -> bool:
    """True only for ``TTS_OBS=1`` (full mode). ``TTS_OBS=host`` records
    host events but leaves every device program untouched."""
    return os.environ.get("TTS_OBS", "0") == "1"


def init_block():
    """Fresh all-zeros counter block — the dispatch-local carry leaf."""
    import jax.numpy as jnp

    return jnp.zeros((NSLOTS,), jnp.int32)


# tts-lint: traced (called from the resident while-loop body when TTS_OBS=1)
def update(ctr, cnt, n: int, tree_inc, sol_inc, fits, size, push_rows):
    """One cycle's accumulation: pure elementwise jnp on a (NSLOTS,) int32
    vector. ``cnt``/``tree_inc``/``sol_inc``/``size``/``push_rows`` are
    traced scalars from the loop body, ``fits`` the fused-path predicate,
    ``n`` the static child-slot count."""
    import jax.numpy as jnp

    inc = jnp.stack([
        cnt,
        tree_inc,
        sol_inc,
        cnt * n - tree_inc - sol_inc,
        jnp.where(fits, 0, 1).astype(jnp.int32),
        jnp.int32(0),
        jnp.int32(0),
        push_rows,
    ])
    hwm = jnp.stack([
        jnp.int32(0), jnp.int32(0), jnp.int32(0), jnp.int32(0),
        jnp.int32(0), size, tree_inc, jnp.int32(0),
    ])
    return jnp.maximum(ctr + inc, hwm)


def merge_host(total: dict | None, block) -> dict:
    """Host-side accumulation of one harvested block (np array, possibly
    (D, NSLOTS) for the mesh tiers) into a running totals dict — adds the
    additive slots, maxes the high-water marks."""
    import numpy as np

    arr = np.asarray(block, dtype=np.int64).reshape(-1, NSLOTS)
    out = dict(total) if total else {name: 0 for name in SLOTS}
    for i, name in enumerate(SLOTS):
        col = arr[:, i]
        if i in _MAX_SLOTS:
            out[name] = max(out[name], int(col.max()))
        else:
            out[name] = out[name] + int(col.sum())
    return out


def as_args(block) -> dict:
    """A harvested block as a {slot: int} dict for counter events and
    metrics lines (multi-shard blocks sum the additive slots and max the
    high-water marks, like ``merge_host``)."""
    return merge_host(None, block)


# -- compiled-program contracts (`tts check`, analysis/contracts.py) --------
# The zero-cost-disabled-path claim of the module docstring, as checked
# contracts (previously a one-cell jaxpr pin in tests/test_obs.py).

from ..analysis.contracts import contract


@contract(
    "obs-off-identity",
    claim="TTS_OBS unset, =0, and =host build byte-identical resident step "
          "jaxprs with the original 7-leaf carry — counters are compiled "
          "OUT when off, never branched (host mode touches no device "
          "program)",
    artifact="variants",
)
def _contract_obs_off_identity(art, cell):
    if not art.has("off", "obs0", "obs-host"):
        return []
    out = []
    if not (art.text("off") == art.text("obs0") == art.text("obs-host")):
        out.append("disabled/host obs builds are not byte-identical to the "
                   "unset build (a counter leaked into the off path)")
    for lb in ("off", "obs0", "obs-host"):
        if art.outvars(lb) != 7:
            out.append(f"{lb} build carries {art.outvars(lb)} output leaves "
                       "(the counter-free step carries 7)")
    return out


@contract(
    "obs-counter-block",
    claim="TTS_OBS=1 adds exactly ONE output leaf (the counter block) and "
          "genuinely changes the program — the armed variant is a "
          "distinct compilation, not a branch",
    artifact="variants",
)
def _contract_obs_counter_block(art, cell):
    if not art.has("off", "obs1"):
        return []
    out = []
    if art.outvars("obs1") != art.outvars("off") + 1:
        out.append(
            f"armed obs build carries {art.outvars('obs1')} output leaves "
            f"(expected {art.outvars('off') + 1}: base + the counter block)"
        )
    if art.text("obs1") == art.text("off"):
        out.append("armed obs build is byte-identical to the off build "
                   "(the counter block is silently gone)")
    return out
