"""Anytime search-quality telemetry (``TTS_QUALITY``): the incumbent
trajectory, recorded host-side at dispatch boundaries.

Large-scale B&B work reports *solution quality over time*, not just
nodes/s (Helbecque et al., arXiv:2012.09511 §5 plot exactly this curve);
a serving daemon needs it live — "how good is the answer so far" is the
question a tenant asks of a running job. This module records the
trajectory: one point per incumbent improvement, carrying

  ``(t_s, step, best, nodes)``

— wall-time since the first observation, cumulative dispatch step,
the new incumbent, and nodes expanded so far. The first observed
incumbent is always recorded (it anchors the curve at t≈0; for a
warm-started PFSP run that is the table UB, for N-Queens the INF
sentinel of a problem with no objective).

Cost model: the recorder consumes scalars the dispatch loop ALREADY
reads at its host boundary (``program.read_scalars``) — no new carry
state, no extra device work, and the compiled step is byte-identical
with the knob on or off (pinned by the ``quality-off-identity``
contract below, audited by ``tts check`` over the knob matrix). Off
path: one ``tracker()`` call per run returning ``None``, one ``is not
None`` check per dispatch.

Arming: ``TTS_QUALITY=1`` for standalone CLI/bench runs (the trajectory
lands in ``SearchResult.quality``); the serve scheduler instead *binds*
a per-job recorder (``with bound(rec):``) that is always on and spans
preemption slices, so a job's curve survives requeues and the final
slice's result carries the full-job trajectory.
"""

from __future__ import annotations

import os
import threading

from . import events as ev


def enabled() -> bool:
    """The ``TTS_QUALITY`` knob: unset/``0`` = off, ``1`` = record the
    incumbent trajectory into ``SearchResult.quality``. Host-side only —
    flipping it never recompiles anything."""
    return os.environ.get("TTS_QUALITY", "0") not in ("", "0")


class QualityRecorder:
    """Thread-safe incumbent-trajectory recorder.

    One per run — or one per serve *job*, where it spans preemption
    slices: ``step_offset`` is set to the job's cumulative step count
    before each slice so recorded steps stay job-cumulative, and the
    wall-clock base persists across slices (queue wait between slices is
    real anytime latency and stays in the curve). The mesh/dist tiers'
    host threads may share one recorder; the lock makes concurrent
    observes merge into a single monotone trajectory."""

    def __init__(self, optimum: int | None = None):
        self._lock = threading.Lock()
        self._points: list[dict] = []  # guarded-by: _lock
        self._best: int | None = None  # guarded-by: _lock
        self._t0_us: float | None = None  # guarded-by: _lock
        #: Best-known reference for primal-gap computation (None = unknown).
        self.optimum = optimum
        #: Steps recorded before this slice (serve preemption resumes).
        self.step_offset = 0

    def observe(self, best, step: int, nodes: int,
                t_us: float | None = None) -> bool:
        """Record ``best`` if it improves on the last recorded incumbent
        (the first observation always records). Returns True when a
        point was appended."""
        best = int(best)
        now = ev.now_us() if t_us is None else t_us
        with self._lock:
            if self._best is not None and best >= self._best:
                return False
            if self._t0_us is None:
                self._t0_us = now
            self._best = best
            self._points.append({
                "t_s": round(max(0.0, now - self._t0_us) / 1e6, 6),
                "step": int(self.step_offset) + int(step),
                "best": best,
                "nodes": int(nodes),
            })
            return True

    def points(self) -> list[dict]:
        """Snapshot of the trajectory so far (serve streams new entries
        as SSE ``incumbent`` frames)."""
        with self._lock:
            return list(self._points)

    def result(self) -> dict:
        """The ``SearchResult.quality`` payload."""
        with self._lock:
            return {"optimum": self.optimum, "points": list(self._points)}


# -- per-thread binding (serve: one recorder per job) -----------------------

_TLS = threading.local()


def current() -> QualityRecorder | None:
    """The recorder bound to this thread, if any."""
    return getattr(_TLS, "rec", None)


class bound:
    """``with quality.bound(rec):`` — route this thread's ``tracker()``
    to a caller-owned recorder (regardless of TTS_QUALITY; the serve
    scheduler wraps each slice so per-job quality is always on)."""

    def __init__(self, rec: QualityRecorder | None):
        self._rec = rec
        self._prev = None

    def __enter__(self):
        self._prev = getattr(_TLS, "rec", None)
        _TLS.rec = self._rec
        return self._rec

    def __exit__(self, *exc):
        _TLS.rec = self._prev
        return False


def tracker(problem=None) -> QualityRecorder | None:
    """The recorder an engine run should observe into: the thread-bound
    one (serve) if present, a fresh one when ``TTS_QUALITY=1``, else
    ``None`` (the off path). Resolves the problem's best-known reference
    into ``rec.optimum`` and, when event tracing is on, emits one
    ``quality_ref`` event so a merged trace can compute gaps offline."""
    rec = current()
    if rec is None:
        if not enabled():
            return None
        rec = QualityRecorder()
    if rec.optimum is None and problem is not None:
        from ..problems import taillard_optima

        rec.optimum = taillard_optima.optimum_for(problem)
    if rec.optimum is not None and ev.enabled():
        label = getattr(problem, "name", "?") if problem is not None else "?"
        inst = getattr(problem, "inst", None) if problem is not None else None
        if isinstance(inst, int):
            label = f"ta{inst:03d}"
        ev.emit("quality_ref", args={
            "instance": label, "optimum": int(rec.optimum),
        })
    return rec


# -- anytime metrics (arXiv:2012.09511 §5 conventions) ----------------------

def primal_gap(best, optimum) -> float | None:
    """Relative gap ``(best - optimum) / optimum``; None when unknown."""
    from ..problems import taillard_optima

    return taillard_optima.gap(best, optimum)


def primal_integral(points: list[dict], optimum, horizon_s: float,
                    cap: float = 1.0) -> float | None:
    """Normalized primal integral over ``[0, horizon_s]``: the
    time-weighted average of the (capped) primal gap, treating the gap
    before the first incumbent as ``cap``. 0.0 = instantly optimal;
    ``cap`` = never found anything useful. None when no reference value
    or horizon exists."""
    if optimum is None or optimum <= 0 or not horizon_s or horizon_s <= 0:
        return None
    total = 0.0
    t_prev = 0.0
    g_prev = cap
    for p in sorted(points or [], key=lambda p: p.get("t_s", 0.0)):
        t = min(max(float(p.get("t_s", 0.0)), 0.0), float(horizon_s))
        total += g_prev * (t - t_prev)
        g = primal_gap(p.get("best"), optimum)
        g_prev = cap if g is None else min(cap, max(g, 0.0))
        t_prev = t
    total += g_prev * (float(horizon_s) - t_prev)
    return total / float(horizon_s)


# -- compiled-program contract (`tts check`, analysis/contracts.py) ---------

from ..analysis.contracts import contract  # noqa: E402


@contract(
    "quality-off-identity",
    claim="quality telemetry is host-side only: it consumes scalars the "
          "dispatch boundary already reads, adds no carry state, and the "
          "TTS_QUALITY=1 build is byte-identical to the off build (same "
          "step jaxpr text, same outvar count) — the knob may never fork "
          "a compilation",
    artifact="variants",
)
def _contract_quality_off_identity(art, cell):
    if not art.has("off", "quality1"):
        return []
    out = []
    if art.text("quality1") != art.text("off"):
        out.append("TTS_QUALITY=1 changed the compiled step jaxpr "
                   "(quality telemetry leaked into the device program)")
    if art.outvars("quality1") != art.outvars("off"):
        out.append(
            f"TTS_QUALITY=1 build carries {art.outvars('quality1')} output "
            f"leaves (off build carries {art.outvars('off')})"
        )
    return out
