"""Crash-safe flight recorder (``TTS_FLIGHTREC``; docs/OBSERVABILITY.md).

Three hardware rounds died on a dead tunnel and left *nothing* behind —
the bench trajectory for PRs 3-5 is literally empty, because every
telemetry artifact was written at end-of-run and the runs never ended.
This module makes a dying run leave a diagnosis:

  * **in-run state**: a bounded ring of periodic snapshots (nodes/s,
    incumbent, pool occupancy, pipeline depth, K, steal totals) plus a
    registry of the **last completed dispatch** per (host, worker) and
    each worker's idle state — harvested only at the dispatch/chunk
    boundaries the engines already own (a ``heartbeat()`` per boundary;
    one global enable check when off, exactly the ``events.emit`` cost
    model), never from inside a device program;
  * **post-mortem dump**: on SIGTERM, SIGALRM, an unhandled exception, or
    a watchdog stall (no heartbeat for ``TTS_WATCHDOG_S`` — the hung-
    dispatch signature of a dead tunnel), the recorder drains the event
    buffers and writes a valid Chrome-trace JSON plus a metrics JSONL,
    fsync'd, with the last-dispatch registry / in-flight pipeline depth /
    idle map embedded in the trace's ``otherData.flightrec`` — so ``tts
    report`` and Perfetto work on the corpse exactly as on a clean trace.

Guard safety: everything here is host-side bookkeeping at existing host
control points. Device programs, jaxprs, and the steady-state guard are
untouched (tests/test_flightrec.py pins the disabled path and a green
guarded run with recording armed).

Knobs: ``TTS_FLIGHTREC=<path-prefix>`` arms recording and names the dump
files ``<prefix>.trace.json`` / ``<prefix>.metrics.jsonl`` (armed even
with ``TTS_OBS`` off — snapshots and the dispatch registry need no event
buffers); ``TTS_FLIGHTREC=0`` disables; unset, recording rides ``TTS_OBS``
with a ``tts_flightrec`` prefix in the temp dir. ``TTS_WATCHDOG_S`` sets
the stall threshold (default 300; ``0`` disables the watchdog thread).
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time
from collections import deque

from . import events as ev

#: Snapshot ring bound: at the default cadence (~4/s peak) this holds the
#: last several minutes of run dynamics; older snapshots age out.
RING_SNAPSHOTS = 512

#: Minimum microseconds between ring snapshots — heartbeats arrive once
#: per dispatch (possibly hundreds/s on fast configs); the ring keeps a
#: low-overhead subsample, not every boundary.
SNAPSHOT_PERIOD_US = 250_000.0

#: Default watchdog stall threshold (seconds without a heartbeat after at
#: least one arrived). The tunnel's observed failure mode is a dispatch
#: that never returns — minutes-long legitimate dispatches exist (large
#: instance compiles ride the first dispatch), so the default is lax;
#: hardware sessions can tighten it per stage.
WATCHDOG_DEFAULT_S = 300.0

def _knob() -> str:
    return os.environ.get("TTS_FLIGHTREC", "") or ""


def enabled() -> bool:
    """Recording armed? ``TTS_FLIGHTREC=0`` force-disables; any other
    explicit value arms it; unset, it rides ``TTS_OBS``."""
    knob = _knob()
    if knob == "0":
        return False
    if knob:
        return True
    return ev.enabled()


def dump_prefix() -> str:
    """Dump path prefix: an explicit ``TTS_FLIGHTREC`` path wins; the
    implicit default lands in the temp dir — a TTS_OBS=1 test/CI session
    must never dirty a working tree with post-mortems (armed hardware
    sessions always set the path)."""
    knob = _knob()
    if knob not in ("", "0", "1"):
        return knob
    import tempfile

    return os.path.join(tempfile.gettempdir(), "tts_flightrec")


def watchdog_interval_s() -> float:
    raw = os.environ.get("TTS_WATCHDOG_S", "")
    try:
        return float(raw) if raw else WATCHDOG_DEFAULT_S
    except ValueError:
        return WATCHDOG_DEFAULT_S


def _aggregate(now: float, tier: str, last: list[dict], idle_count: int,
               meta: dict, prev: dict | None) -> dict:
    """One global snapshot from (copies of) the per-worker dispatch
    registry; rates are deltas against the previous snapshot."""
    tree = sum(d["tree"] for d in last)
    sol = sum(d["sol"] for d in last)
    bests = [d["best"] for d in last if d["best"] is not None]
    sizes = [d["size"] for d in last if d["size"] is not None]
    nps = 0.0
    if prev is not None and now > prev["ts_us"]:
        nps = max(0.0, (tree - prev["tree"]) * 1e6 / (now - prev["ts_us"]))
    # Latest harvested phase split (TTS_PHASEPROF runs): the newest
    # registry entry that carries one names the dominant phase.
    ph = None
    for d in sorted(last, key=lambda d: d["ts_us"]):
        if d.get("phases"):
            ph = d["phases"]
    snap_phase: dict = {}
    if ph is not None:
        from . import phases as phases_mod

        snap_phase["phases"] = dict(ph)
        dom = phases_mod.dominant_phase(ph)
        if dom is not None:
            snap_phase["dominant_phase"] = dom[0]
            snap_phase["dominant_phase_share"] = round(dom[1], 4)
    # Job correlation (serve): the scheduler stamps the bound recorder's
    # meta with the job id/class; surfacing them here puts the job on
    # every SSE frame and dumped snapshot.
    for k in ("job", "cls"):
        if meta.get(k) is not None:
            snap_phase[k] = meta[k]
    # Most recent steal's link class / hierarchy level (worker heartbeats
    # or the inter-host communicator's note_steal): on a stall, this names
    # the level the run was last fed from.
    if meta.get("steal_link") is not None:
        snap_phase["steal_link"] = meta["steal_link"]
        snap_phase["steal_level"] = meta.get("steal_level")
    return {
        **snap_phase,
        "ts_us": now,
        "tier": tier,
        "seq": max((d["seq"] for d in last), default=0),
        "tree": tree,
        "sol": sol,
        "nodes_per_sec": round(nps, 1),
        "best": min(bests) if bests else None,
        "size": sum(sizes) if sizes else None,
        "inflight": max((d["inflight"] for d in last), default=0),
        "steals": sum(d["steals"] for d in last),
        "workers": len(last),
        "idle_workers": idle_count,
        "depth": meta.get("depth", 1),
        "K": meta.get("K"),
    }


class FlightRecorder:
    """Snapshot ring + last-dispatch registry + crash-dump hooks.

    One module-level instance serves the process; the class is separate so
    tests can exercise ring bounds and dump content without touching the
    global handlers.
    """

    def __init__(self, ring: int = RING_SNAPSHOTS,
                 snapshot_period_us: float = SNAPSHOT_PERIOD_US,
                 always_on: bool = False):
        # always_on: a privately-owned recorder (the serve daemon binds one
        # per job) records regardless of the TTS_FLIGHTREC/TTS_OBS knobs —
        # the binding itself is the opt-in; it never installs process-wide
        # dump hooks.
        self.always_on = always_on
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=ring)  # guarded-by: _lock
        self._last: dict = {}  # guarded-by: _lock -- (host, wid) -> dispatch
        self._idle: set = set()  # guarded-by: _lock -- (host, wid) idle now
        self._meta: dict = {}  # guarded-by: _lock -- run tier/label/depth/K
        self._prev_snap: dict | None = None  # guarded-by: _lock
        self._snap_period_us = snapshot_period_us
        self._last_beat: float | None = None  # monotonic s; advisory read
        self._stall_dumped = False
        self._installed = False
        self._watchdog: threading.Thread | None = None
        self._prev_handlers: dict = {}
        self._prev_excepthook = None

    # -- in-run state ------------------------------------------------------

    def heartbeat(self, tier: str, host: int = 0, wid: int = 0, *,
                  seq: int = 0, cycles: int = 0, size: int | None = None,
                  best: int | None = None, tree: int = 0, sol: int = 0,
                  depth: int = 1, K: int | None = None, inflight: int = 0,
                  steals: int = 0, phases: dict | None = None,
                  steal_link: str | None = None,
                  steal_level: int | None = None) -> None:
        """One completed dispatch/chunk boundary. Updates the registry,
        feeds the watchdog, and (rate-limited) appends a ring snapshot +
        emits a ``snapshot`` counter sample into the event stream.
        ``phases`` is the run's per-phase ns totals so far (TTS_PHASEPROF
        armed runs) — a watchdog post-mortem then names where the last
        dispatch was spending its cycles. ``steal_link``/``steal_level``
        name the worker's most recent steal's link class and hierarchy
        level (parallel/topology.py) so a stalled run's snapshot shows
        which steal level it was living off."""
        if not (self.always_on or enabled()):
            return
        now = ev.now_us()
        self._last_beat = time.monotonic()
        self._stall_dumped = False
        with self._lock:
            entry = {
                "ts_us": now, "seq": seq, "cycles": cycles, "size": size,
                "best": best, "tree": tree, "sol": sol, "inflight": inflight,
                "steals": steals,
            }
            if phases is not None:
                entry["phases"] = dict(phases)
            if steal_link is not None:
                self._meta["steal_link"] = steal_link
                self._meta["steal_level"] = steal_level
            self._last[(host, wid)] = entry
            self._idle.discard((host, wid))
            self._meta.setdefault("tier", tier)
            self._meta["depth"] = depth
            if K is not None:
                self._meta["K"] = K
            prev = self._prev_snap
            if prev is not None and now - prev["ts_us"] < self._snap_period_us:
                return
            snap = _aggregate(now, tier, list(self._last.values()),
                              len(self._idle), dict(self._meta), prev)
            self._ring.append(snap)
            self._prev_snap = snap
        # Outside the lock: the event recorder has its own buffers.
        ev.counter("snapshot", host=host, **{
            k: v for k, v in snap.items()
            if isinstance(v, (int, float)) and k != "ts_us"
        })

    def set_idle(self, host: int, wid: int, idle: bool) -> None:
        """Worker idle-state transitions (the offload tiers' busy<->idle
        edges — same call sites as their ``idle`` spans)."""
        if not (self.always_on or enabled()):
            return
        with self._lock:
            if idle:
                self._idle.add((host, wid))
            else:
                self._idle.discard((host, wid))

    def note_steal(self, host: int, link: str, level: int) -> None:
        """Record a work-migration arrival's link class / hierarchy level
        without a full heartbeat — the inter-host communicator thread's
        call site (dist/dist_mesh donation receive): the next snapshot
        (and a stall post-mortem) then names the level feeding the run."""
        if not (self.always_on or enabled()):
            return
        with self._lock:
            self._meta["steal_link"] = link
            self._meta["steal_level"] = level

    def snapshots(self, n: int | None = None) -> list[dict]:
        with self._lock:
            out = list(self._ring)
        return out if n is None else out[-n:]

    def latest(self) -> dict | None:
        with self._lock:
            return self._ring[-1] if self._ring else None

    def state(self) -> dict:
        """The post-mortem payload: last completed dispatch per track,
        in-flight depth, idle map, run meta."""
        with self._lock:
            return {
                "last_dispatch": {
                    f"h{h}/w{w}": dict(d)
                    for (h, w), d in sorted(self._last.items())
                },
                "idle_workers": sorted(
                    f"h{h}/w{w}" for h, w in self._idle
                ),
                "meta": dict(self._meta),
                "snapshots": len(self._ring),
            }

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self._last.clear()
            self._idle.clear()
            self._meta.clear()
            self._prev_snap = None
        self._last_beat = None
        self._stall_dumped = False

    # -- dump --------------------------------------------------------------

    def dump(self, reason: str, prefix: str | None = None) -> str | None:
        """Write ``<prefix>.trace.json`` + ``<prefix>.metrics.jsonl``.

        Safe to call from a signal handler or the watchdog thread: the
        event drain uses a bounded lock wait (the interrupted thread could
        hold a buffer-registry lock), writes are fsync'd, and any failure
        returns None instead of raising — a dump must never turn a dying
        process's exit into a different error."""
        from . import export

        try:
            prefix = prefix or dump_prefix()
            evts = ev.drain(timeout=2.0)
            obj = export.chrome_trace_object(evts, label="flightrec")
            obj["otherData"]["flightrec"] = {
                "reason": reason,
                "dumped_unix": time.time(),
                **self.state(),
            }
            trace_path = prefix + ".trace.json"
            with open(trace_path, "w") as f:
                json.dump(obj, f)
                f.flush()
                os.fsync(f.fileno())
            metrics_path = prefix + ".metrics.jsonl"
            with open(metrics_path, "w") as f:
                for rec in export.metrics_lines(evts):
                    f.write(json.dumps(rec) + "\n")
                for snap in self.snapshots():
                    f.write(json.dumps({"name": "snapshot", **snap}) + "\n")
                f.flush()
                os.fsync(f.fileno())
            return trace_path
        except Exception:  # noqa: BLE001 — never mask the original death
            return None

    # -- hooks -------------------------------------------------------------

    def install(self) -> bool:
        """Arm the dump triggers (idempotent). Signal handlers only attach
        from the main thread (Python's rule); the excepthook and watchdog
        attach from anywhere. Returns True when armed."""
        if not enabled():
            return False
        if not self._installed:
            self._installed = True
            self._prev_excepthook = sys.excepthook
            sys.excepthook = self._on_exception
        # Signals (re-)attempt on every arm: the FIRST install may have
        # come from a worker thread (dist_mesh virtual hosts), where
        # Python forbids signal handlers — a later main-thread arm must
        # still attach them.
        if (not self._prev_handlers
                and threading.current_thread() is threading.main_thread()):
            for sig in (signal.SIGTERM, signal.SIGALRM):
                try:
                    self._prev_handlers[sig] = signal.signal(
                        sig, self._on_signal
                    )
                except (ValueError, OSError):
                    pass
        self._maybe_start_watchdog()
        return True

    def _maybe_start_watchdog(self) -> None:
        if self._watchdog is not None and self._watchdog.is_alive():
            return
        interval = watchdog_interval_s()
        if interval <= 0:
            return
        self._watchdog = threading.Thread(
            target=self._watchdog_loop, args=(interval,),
            name="tts-flightrec-watchdog", daemon=True,
        )
        self._watchdog.start()

    def _watchdog_loop(self, interval: float) -> None:
        # Advisory reads of _last_beat (a float assignment is atomic); the
        # dump itself takes the lock with a bounded wait.
        poll = max(1.0, interval / 4.0)
        while True:
            time.sleep(poll)
            if not enabled():
                continue
            beat = self._last_beat
            if beat is None or self._stall_dumped:
                continue
            stalled = time.monotonic() - beat
            if stalled > interval:
                self._stall_dumped = True
                self.dump(f"watchdog_stall: no dispatch heartbeat for "
                          f"{stalled:.0f}s (threshold {interval:.0f}s)")

    def _on_signal(self, signum, frame) -> None:
        name = signal.Signals(signum).name
        self.dump(name)
        prev = self._prev_handlers.get(signum)
        if callable(prev):
            prev(signum, frame)
            return
        # Default/ignored previous disposition: restore it and re-raise so
        # the process exits with the honest signal status (e.g. 143).
        signal.signal(signum, prev if prev is not None else signal.SIG_DFL)
        os.kill(os.getpid(), signum)

    def _on_exception(self, exc_type, exc, tb) -> None:
        # KeyboardInterrupt is an operator action, not a crash worth a
        # post-mortem; everything else dumps before the traceback prints.
        if not issubclass(exc_type, KeyboardInterrupt):
            self.dump(f"exception: {exc_type.__name__}: {exc}")
        hook = self._prev_excepthook or sys.__excepthook__
        hook(exc_type, exc, tb)


_REC = FlightRecorder()

#: Thread-bound recorder override (``bound()``): the serve daemon runs many
#: tenant jobs in one process and namespaces each job's telemetry by
#: binding a private recorder around the engine call — the engines keep
#: calling the same module-level ``heartbeat``/``set_idle`` hooks, and the
#: binding routes them. Thread-local because jobs run on scheduler worker
#: threads; an unbound thread (every standalone run) uses the global
#: recorder exactly as before.
_TLS = threading.local()


def recorder() -> FlightRecorder:
    return _REC


def current() -> FlightRecorder:
    """The recorder this thread's heartbeats land in: the ``bound()``
    recorder when inside a binding, else the process-global one."""
    return getattr(_TLS, "rec", None) or _REC


class bound:
    """Context manager: route this thread's heartbeats/idle edges into
    ``rec`` (re-entrant; restores the previous binding on exit)."""

    def __init__(self, rec: FlightRecorder):
        self.rec = rec
        self._prev: FlightRecorder | None = None

    def __enter__(self) -> FlightRecorder:
        self._prev = getattr(_TLS, "rec", None)
        _TLS.rec = self.rec
        return self.rec

    def __exit__(self, *exc) -> None:
        _TLS.rec = self._prev


def arm(tier: str | None = None) -> bool:
    """Engine entry hook: install the dump triggers if recording is
    enabled (cheap no-op otherwise) and note the run's tier. Under a
    ``bound()`` recorder the tier lands on the binding and no process-wide
    hooks are touched — a tenant job must not re-point the daemon's signal
    handlers or watchdog."""
    rec = getattr(_TLS, "rec", None)
    if rec is not None:
        if tier is not None:
            with rec._lock:
                rec._meta["tier"] = tier
        return True
    ok = _REC.install()
    if ok and tier is not None:
        with _REC._lock:
            _REC._meta["tier"] = tier
    return ok


def heartbeat(*args, **kw) -> None:
    current().heartbeat(*args, **kw)


def set_idle(host: int, wid: int, idle: bool) -> None:
    current().set_idle(host, wid, idle)


def note_steal(host: int, link: str, level: int) -> None:
    current().note_steal(host, link, level)


def snapshots(n: int | None = None) -> list[dict]:
    return _REC.snapshots(n)


def latest() -> dict | None:
    return _REC.latest()


def dump(reason: str, prefix: str | None = None) -> str | None:
    return _REC.dump(reason, prefix)


def reset() -> None:
    _REC.reset()
