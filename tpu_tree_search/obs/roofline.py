"""Memory-roofline audit for the resident cycle (ROADMAP item 3).

Every phase of the chunk cycle (pop/eval/compact/push/overflow,
obs/phases.py) is memory-bound at the pool shapes this engine runs — the
bound math is a handful of small matmuls per node while every node's row
crosses HBM at least twice per cycle.  So the honest performance question
per phase is not FLOP/s but "what fraction of the memory-bound peak does
the measured time reach":

    pct_of_peak = (analytic byte FLOOR per cycle * cycles)
                  / (peak HBM bytes/s * measured phase seconds)

The three inputs come from machinery that already exists:

  * measured per-phase ns — the `tts profile` phase-clock splits
    (TTS_PHASEPROF=1, obs/phases.py), summed over the run;
  * cycles — the `dispatch` spans' ``cycles`` args (obs/events.py), or
    the host loop's own accumulation for the in-process SearchResult;
  * peak bytes/s — resolved in order from ``TTS_HBM_GBPS`` (explicit
    override), a measured COSTMODEL.json ``hbm`` link fit
    (``links.hbm.per_sec``, bytes/s — bankable by a hardware-session
    microbench), then the nominal per-backend table below.

The byte counts are analytic FLOORS — the bytes the phase MUST move
(pool rows in, survivor rows out), not what XLA happens to materialize —
so ``pct_of_peak`` reads as "how close to unavoidable"; a low percentage
names a phase whose intermediates are round-tripping (the megakernel's
whole reason to exist), and the streamed megakernel's win shows up as the
fused ``eval`` row approaching its floor.  Percentages are per measured
run; the model never feeds back into routing.

Surfaces: ``tts report --roofline`` (table per trace, via the
``roofline_meta`` event the resident loop emits), ``SearchResult.
roofline`` (armed whenever the phase profiler ran), and the bench
megakernel A/B records (``roofline_mem`` — the bench's FLOP-based
``roofline`` MFU field is a different axis and keeps its name).
"""

from __future__ import annotations

import os

from . import phases as obs_phases

#: Nominal peak HBM bandwidth per backend, GB/s — the LAST-RESORT fallback
#: when neither ``TTS_HBM_GBPS`` nor a measured COSTMODEL ``hbm`` link fit
#: is available (`peak_bytes_per_sec` resolves in that order on every
#: backend, gpu included).  Sources:
#:
#:   * ``tpu``  819.0 — TPU v5e datasheet HBM2 bandwidth (the chip class
#:     the hardware sessions target; a v4 is 1228, overridable).
#:   * ``gpu``  900.0 — A100-40GB PCIe class datasheet HBM2e figure,
#:     rounded down; a PLACEHOLDER for whatever card actually runs
#:     `scripts/gpu_session.sh`, which banks the measured figure into
#:     GPU_BASELINE.json and COSTMODEL (an H100 SXM is ~3350, a consumer
#:     4090 ~1008 — always prefer ``TTS_HBM_GBPS`` or a measured fit on
#:     gpu; ``nominal:gpu`` in ``peak_source`` flags an unmeasured run).
#:   * ``cpu``  40.0 — dual-channel DDR4-3200 (25.6) plus margin, so
#:     interpret-mode tables stay finite and obviously non-chip.
#:
#: Keys are raw platforms; forced non-native flavors resolve a compound
#: "platform+kind" profile key (ops/backend.profile_backend) which misses
#: this table and falls through to the cpu row — interpret runs never
#: masquerade as chip-speed rows.
NOMINAL_GBPS = {"tpu": 819.0, "gpu": 900.0, "cpu": 40.0}

#: The cycle phases the audit rows cover (obs/phases.py CYCLE_SLOTS).
PHASES = obs_phases.CYCLE_SLOTS


def hbm_gbps_override() -> float | None:
    """The ``TTS_HBM_GBPS`` knob: explicit peak-bandwidth override for the
    roofline denominator (GB/s)."""
    raw = os.environ.get("TTS_HBM_GBPS")
    if raw is None or raw == "":
        return None
    v = float(raw)
    if v <= 0:
        raise ValueError(f"TTS_HBM_GBPS must be a positive GB/s figure, "
                         f"got {raw!r}")
    return v


def hbm_entry(profile: dict, backend: str) -> dict | None:
    """First profile entry (sorted for determinism) on ``backend`` that
    carries a measured ``hbm`` link fit — the bandwidth is a chip
    property, not a problem-shape one, so any entry qualifies."""
    for key in sorted(profile):
        e = profile[key]
        if not isinstance(e, dict) or e.get("backend") != backend:
            continue
        hbm = (e.get("links") or {}).get("hbm")
        if isinstance(hbm, dict) and hbm.get("per_sec"):
            return e
    return None


def peak_bytes_per_sec(backend: str, entry: dict | None = None
                       ) -> tuple[float, str]:
    """Resolve the roofline denominator: (bytes/s, source) — env override,
    then a measured COSTMODEL ``hbm`` link, then the nominal table."""
    env = hbm_gbps_override()
    if env is not None:
        return env * 1e9, "env:TTS_HBM_GBPS"
    if entry is not None:
        hbm = (entry.get("links") or {}).get("hbm")
        if isinstance(hbm, dict) and hbm.get("per_sec"):
            return float(hbm["per_sec"]), "costmodel:hbm"
    gbps = NOMINAL_GBPS.get(backend, NOMINAL_GBPS["cpu"])
    return gbps * 1e9, f"nominal:{backend}"


def phase_byte_floors(*, M: int, n: int, S: int, itemsize: int,
                      aux_itemsize: int = 4, megakernel: bool = False
                      ) -> dict[str, int]:
    """Analytic HBM byte floor per CYCLE for each phase — the bytes the
    phase must move at pool dtype, not what XLA materializes.

    Off path: ``pop`` slices the (M, node) chunk out of the pool; ``eval``
    reads the chunk and writes the (M*n) int32 bound/keep plane;
    ``compact`` reads the keep plane and writes the (S,) survivor ids;
    ``push`` gathers S survivor rows and writes them back (2x S rows at
    node width).  ``overflow`` is the fits==False branch — it moves the
    whole M*n reservation, but only on overflow cycles, which the floor
    model cannot apportion from totals alone; it is floored at 0 and its
    row reports measured time with no percentage.

    Megakernel path: the profiler charges the whole fused cycle into
    ``eval`` (engine/resident.py), whose floor is then the streamed pool
    tiles in + the compacted (M*n) int32 rows out of the kernel + the
    engine's pool-dtype write-back of the reserved headroom."""
    node = n * itemsize + aux_itemsize
    Mn = M * n
    if megakernel:
        return {
            "pop": M * node,
            "eval": M * node + Mn * (n + 1) * 4 + Mn * node,
            "compact": 0,
            "push": 0,
            "overflow": 0,
        }
    return {
        "pop": M * node,
        "eval": M * node + Mn * 4,
        "compact": Mn * 4 + S * 4,
        "push": 2 * S * node,
        "overflow": 0,
    }


def audit(phase_ns: dict, cycles: int, *, M: int, n: int, S: int,
          itemsize: int, aux_itemsize: int = 4, megakernel: bool = False,
          peak_bps: float, peak_source: str = "") -> dict:
    """The roofline document: per-phase measured ns, total byte floor,
    achieved GB/s, and %-of-memory-bound-peak.  Phases with no measured
    time or no byte floor report ns only (no percentage — never divide
    by a missing measurement)."""
    floors = phase_byte_floors(M=M, n=n, S=S, itemsize=itemsize,
                               aux_itemsize=aux_itemsize,
                               megakernel=megakernel)
    rows = []
    for slot in PHASES:
        ns = int(phase_ns.get(slot, 0) or 0)
        nbytes = int(floors.get(slot, 0)) * int(cycles)
        row: dict = {"phase": slot, "ns": ns, "bytes": nbytes}
        if ns > 0 and nbytes > 0:
            sec = ns / 1e9
            gbps = nbytes / sec / 1e9
            row["gbps"] = round(gbps, 2)
            row["pct_of_peak"] = round(100.0 * nbytes / (peak_bps * sec), 1)
        rows.append(row)
    return {
        "peak_gbps": round(peak_bps / 1e9, 1),
        "peak_source": peak_source,
        "cycles": int(cycles),
        "phases": rows,
    }


def table(doc: dict) -> list[str]:
    """Render an audit document as the `tts report --roofline` table."""
    lines = [
        f"  roofline (peak {doc['peak_gbps']} GB/s, "
        f"{doc['peak_source']}; {doc['cycles']} cycles):",
        "    phase       time_ms     floor_MB    GB/s     % of peak",
    ]
    for row in doc["phases"]:
        ms = row["ns"] / 1e6
        mb = row["bytes"] / 2**20
        if "pct_of_peak" in row:
            tail = f"{row['gbps']:>8.2f}  {row['pct_of_peak']:>8.1f}%"
        else:
            tail = f"{'-':>8}  {'-':>9}"
        lines.append(
            f"    {row['phase']:<10}{ms:>10.2f}{mb:>13.2f}{tail}"
        )
    return lines


# -- engine/report adapters -------------------------------------------------


def meta_args(program) -> dict:
    """The ``roofline_meta`` event payload the resident loop emits — the
    static shape/routing facts `tts report --roofline` needs to rebuild
    the byte floors from a trace alone."""
    import numpy as np

    try:
        from ..ops import backend as BK

        backend = BK.profile_backend(getattr(program, "device", None))
    except Exception:
        backend = "cpu"
    vals_dt = program.pool_fields[0][1]
    aux_dt = program.pool_fields[1][1]
    return {
        "M": int(program.M),
        "n": int(program.problem.child_slots),
        "S": int(program.S),
        "itemsize": int(np.dtype(vals_dt).itemsize),
        "aux_itemsize": int(np.dtype(aux_dt).itemsize),
        "megakernel": bool(program.megakernel.enabled),
        "megakernel_mt": int(program.megakernel.mt),
        "megakernel_grid": int(program.megakernel.grid),
        "backend": backend,
    }


def from_meta(meta: dict, phase_ns: dict, cycles: int,
              costmodel: dict | None = None) -> dict | None:
    """Build the audit from a ``roofline_meta`` args dict + phase totals —
    the shared path of `tts report --roofline` and the in-process
    SearchResult field."""
    if not phase_ns or cycles <= 0:
        return None
    backend = meta.get("backend") or "cpu"
    entry = hbm_entry(costmodel, backend) if costmodel else None
    peak, src = peak_bytes_per_sec(backend, entry)
    return audit(
        phase_ns, cycles,
        M=int(meta["M"]), n=int(meta["n"]), S=int(meta["S"]),
        itemsize=int(meta.get("itemsize", 4)),
        aux_itemsize=int(meta.get("aux_itemsize", 4)),
        megakernel=bool(meta.get("megakernel")),
        peak_bps=peak, peak_source=src,
    )


def result_audit(program, phase_ns: dict | None, cycles: int) -> dict | None:
    """The SearchResult.roofline payload: audit the finished run's phase
    totals against the resolved peak (COSTMODEL profile when
    TTS_COSTMODEL points at one)."""
    if not phase_ns or cycles <= 0:
        return None
    from . import costmodel as CM

    prof = None
    path = CM.costmodel_path()
    if path:
        prof = CM.load(path)
    return from_meta(meta_args(program), phase_ns, cycles, costmodel=prof)
