"""``tts report <trace>`` — summarize a recorded trace.

Consumes the Chrome-trace JSON written by ``--trace`` (or a drained event
list) and prints the three summaries the load-balancing literature reads
off exactly this kind of per-round telemetry (Helbecque et al.,
arXiv:2012.09511; Melab et al., arXiv:0809.3285):

  * **steal efficiency** — successful steals / attempts, nodes moved,
    plus the inter-host donation and exchange-round totals;
  * **idle fraction per worker** — recorded idle spans over the trace
    span, the direct per-worker imbalance metric;
  * **cycle-rate timeline** — bucketed device cycles/sec and explored
    nodes/sec over the run, from the per-dispatch events.

All three sections always print (zeros / "none recorded" when a tier has
no such events) so downstream tooling can parse unconditionally.
"""

from __future__ import annotations

import json

from .events import COMM_TID


def _span_us(evts: list[dict]) -> tuple[float, float]:
    if not evts:
        return 0.0, 0.0
    t0 = min(e.get("ts", 0.0) for e in evts)
    t1 = max(e.get("ts", 0.0) + e.get("dur", 0.0) for e in evts)
    return t0, t1


def summarize(evts: list[dict], buckets: int = 10,
              costmodel: dict | None = None) -> dict:
    """Structured summary of a drained/loaded event list.

    ``costmodel`` is an optional loaded COSTMODEL.json profile
    (obs/costmodel.py) consulted for a measured ``hbm`` link when the
    trace carries the inputs for a roofline audit."""
    t0, t1 = _span_us(evts)
    span_s = max(t1 - t0, 0.0) / 1e6

    # -- steal / donation efficiency --------------------------------------
    steals = [e for e in evts if e.get("name") == "steal"]
    misses = [e for e in evts if e.get("name") == "steal_miss"]
    attempts = len(steals) + len(misses)
    stolen_nodes = sum((e.get("args") or {}).get("nodes", 0) for e in steals)
    sends = [e for e in evts if e.get("name") == "donate_send"]
    recvs = [e for e in evts if e.get("name") == "donate_recv"]
    rounds = sum(1 for e in evts if e.get("name") == "exchange")
    steal = {
        "attempts": attempts,
        "successes": len(steals),
        "efficiency": (len(steals) / attempts) if attempts else None,
        "nodes_moved": stolen_nodes,
        "interhost_blocks_sent": len(sends),
        "interhost_nodes_sent": sum(
            (e.get("args") or {}).get("nodes", 0) for e in sends
        ),
        "interhost_blocks_received": len(recvs),
        "exchange_rounds": rounds,
    }

    # -- per-link-class steal table (TTS_STEAL, parallel/topology.py) ------
    # Steal-path events stamp their link class (local / ici / dcn) and
    # hierarchy level; bucketing them per class is the observable form of
    # the two-level policy. Acquisition cost is the span duration of the
    # events that DELIVER work (local steal + donate_recv) — the same
    # samples the cost model's steal/donate fits consume; donate_send
    # counts as the inter-host attempt. Traces predating the stamps (no
    # "link" arg) simply produce an empty table.
    steal_links: dict = {}

    def _link_bucket(link: str) -> dict:
        return steal_links.setdefault(link, {
            "attempts": 0, "hits": 0, "misses": 0,
            "nodes": 0, "bytes": 0, "_cost_us": 0.0, "_cost_n": 0,
        })

    for e in steals:
        a = e.get("args") or {}
        if a.get("link") is None:
            continue
        b = _link_bucket(a["link"])
        b["attempts"] += 1
        b["hits"] += 1
        b["nodes"] += a.get("nodes", 0)
        b["bytes"] += a.get("bytes", 0)
        if "dur" in e:
            b["_cost_us"] += e["dur"]
            b["_cost_n"] += 1
    for e in misses:
        a = e.get("args") or {}
        if a.get("link") is None:
            continue
        b = _link_bucket(a["link"])
        b["attempts"] += 1
        b["misses"] += 1
    for e in sends:
        a = e.get("args") or {}
        if a.get("link") is None:
            continue
        _link_bucket(a["link"])["attempts"] += 1
    for e in recvs:
        a = e.get("args") or {}
        if a.get("link") is None:
            continue
        b = _link_bucket(a["link"])
        b["hits"] += 1
        b["nodes"] += a.get("nodes", 0)
        b["bytes"] += a.get("bytes", 0)
        if "dur" in e:
            b["_cost_us"] += e["dur"]
            b["_cost_n"] += 1
    for b in steal_links.values():
        n = b.pop("_cost_n")
        us = b.pop("_cost_us")
        b["mean_cost_us"] = round(us / n, 1) if n else None

    # -- idle fraction per worker -----------------------------------------
    # Busy time is the UNION of dispatch/chunk spans, not their sum: under
    # pipelined dispatch (TTS_PIPELINE >= 2) a track carries up to `depth`
    # overlapping enqueue->scalars-ready spans at once, and summing them
    # would claim more busy time than wall time — the idle/busy fractions
    # must stay truthful at any depth (docs/OBSERVABILITY.md).
    workers: dict[str, dict] = {}
    busy_ivals: dict[str, list] = {}
    for e in evts:
        tid = e.get("tid", 0)
        if tid == COMM_TID:
            continue
        key = f"h{e.get('pid', 0)}/w{tid}"
        w = workers.setdefault(key, {"idle_us": 0.0, "busy_us": 0.0})
        if e.get("name") == "idle":
            w["idle_us"] += e.get("dur", 0.0)
        elif e.get("name") in ("dispatch", "chunk") and "dur" in e:
            ts = e.get("ts", 0.0)
            busy_ivals.setdefault(key, []).append((ts, ts + e["dur"]))
    for key, ivals in busy_ivals.items():
        ivals.sort()
        total = 0.0
        cur_s, cur_e = ivals[0]
        for s, e_ in ivals[1:]:
            if s <= cur_e:
                cur_e = max(cur_e, e_)
            else:
                total += cur_e - cur_s
                cur_s, cur_e = s, e_
        total += cur_e - cur_s
        workers[key]["busy_us"] = total
    idle = {
        key: {
            "idle_fraction": (w["idle_us"] / (t1 - t0)) if t1 > t0 else 0.0,
            "busy_fraction": (w["busy_us"] / (t1 - t0)) if t1 > t0 else 0.0,
        }
        for key, w in sorted(workers.items())
    }

    # -- cycle-rate timeline ----------------------------------------------
    # Resident tiers emit per-dispatch spans; the offload tiers (multi/
    # dist workers) emit per-chunk spans instead — use whichever exists so
    # every tier gets a rate timeline (chunk events carry no device cycle
    # count; their cycles contribution is 0).
    dispatches = [e for e in evts if e.get("name") == "dispatch"]
    if not dispatches:
        dispatches = [e for e in evts if e.get("name") == "chunk"]
    timeline = []
    if dispatches and t1 > t0:
        nb = min(buckets, max(1, len(dispatches)))
        width = (t1 - t0) / nb
        acc = [{"cycles": 0, "nodes": 0, "dispatches": 0} for _ in range(nb)]
        for e in dispatches:
            # Attribute at completion: the counters were harvested then.
            end = e.get("ts", 0.0) + e.get("dur", 0.0)
            b = min(nb - 1, int((end - t0) / width))
            a = e.get("args") or {}
            acc[b]["cycles"] += a.get("cycles", 0)
            acc[b]["nodes"] += a.get("tree", 0)
            acc[b]["dispatches"] += 1
        for i, a in enumerate(acc):
            sec = width / 1e6
            timeline.append({
                "t_s": round(i * width / 1e6, 3),
                "cycles_per_sec": round(a["cycles"] / sec, 1),
                "nodes_per_sec": round(a["nodes"] / sec, 1),
                "dispatches": a["dispatches"],
            })

    counters_total: dict = {}
    for e in evts:
        if e.get("name") == "device_counters":
            for k, v in (e.get("args") or {}).items():
                if k in ("pool_hwm", "surv_hwm"):
                    counters_total[k] = max(counters_total.get(k, 0), v)
                else:
                    counters_total[k] = counters_total.get(k, 0) + v

    # -- per-phase cycle decomposition (TTS_PHASEPROF, obs/phases.py) ------
    # device_phases counter samples carry per-dispatch nanoseconds per
    # phase; their sum is the run's measured on-device cycle split.
    phases_total: dict = {}
    for e in evts:
        if e.get("name") == "device_phases":
            for k, v in (e.get("args") or {}).items():
                if isinstance(v, (int, float)):
                    phases_total[k] = phases_total.get(k, 0) + v
    phase_decomp = None
    if phases_total.get("total"):
        from . import phases as phases_mod

        phase_decomp = phases_mod.decomp(phases_total)

    # -- memory-roofline audit (obs/roofline.py) ---------------------------
    # Needs three things a phase-profiled trace carries: the static shape/
    # routing facts (the resident loop's `roofline_meta` event), the
    # measured phase splits above, and the per-dispatch device cycle
    # counts. Absent any one of them the section is simply None — the
    # `--roofline` flag turns that into a hard requirement.
    roofline = None
    metas = [e for e in evts if e.get("name") == "roofline_meta"]
    if metas and phases_total.get("total"):
        cycles = sum(
            (e.get("args") or {}).get("cycles", 0) for e in dispatches
        )
        if cycles > 0:
            from . import roofline as roofline_mod

            roofline = roofline_mod.from_meta(
                metas[-1].get("args") or {}, phases_total, cycles,
                costmodel=costmodel,
            )

    # -- survivor-path work split (maintenance vs evaluator) ---------------
    # The resident cycle does two kinds of work: the evaluator bounds every
    # candidate child (pushed + leaves + pruned evaluations), and the
    # survivor path pops/compacts/pushes rows (push_rows — the fused path
    # touches its full budget per cycle regardless of how many children
    # survived).  A device-side clock does not exist, so this is the WORK
    # split; bench.py's eval-only-loop calibration provides the measured
    # time split per compaction mode.
    survivor = None
    if counters_total.get("push_rows"):
        evals = (counters_total.get("pushed", 0)
                 + counters_total.get("leaves", 0)
                 + counters_total.get("pruned", 0))
        pushed = counters_total.get("pushed", 0)
        survivor = {
            "eval_rows": evals,
            "push_rows": counters_total["push_rows"],
            "push_rows_per_survivor": (
                round(counters_total["push_rows"] / pushed, 2) if pushed
                else None
            ),
            "overflow_cycles": counters_total.get("overflow", 0),
        }

    # -- per-job lanes (serve traces; events.job_context stamps) -----------
    # A merged daemon trace interleaves every tenant's events; the job
    # field (stamped by the scheduler around each slice) groups them back
    # into the per-job view an operator reads.
    jobs_seen = sorted({e["job"] for e in evts if e.get("job") is not None})
    job_lanes: dict = {}
    for j in jobs_seen:
        je = [e for e in evts if e.get("job") == j]
        jt0, jt1 = _span_us(je)
        disp = [e for e in je if e.get("name") in ("dispatch", "chunk")]
        bests = [
            b for b in ((e.get("args") or {}).get("best") for e in disp)
            if b is not None
        ]
        # Batched dispatches (serve/batch.py) stamp the slot index and
        # batch width onto each dispatch span; a job that was spliced,
        # cut, and re-admitted legitimately shows more than one slot.
        slots = sorted({
            s for s in ((e.get("args") or {}).get("slot") for e in disp)
            if s is not None
        })
        widths = [
            b for b in ((e.get("args") or {}).get("B") for e in disp)
            if b is not None
        ]
        job_lanes[j] = {
            "events": len(je),
            "dispatches": len(disp),
            "span_s": round(max(jt1 - jt0, 0.0) / 1e6, 6),
            "best": min(bests) if bests else None,
            "slots": slots or None,
            "batch_width": max(widths) if widths else None,
        }

    # -- anytime quality (obs/quality.py; incumbent + quality_ref events) --
    refs = [e for e in evts if e.get("name") == "quality_ref"]
    ref_args = (refs[-1].get("args") or {}) if refs else {}
    optimum = ref_args.get("optimum")
    incumbents = [e for e in evts if e.get("name") == "incumbent"]
    quality = None
    if incumbents:
        from . import quality as quality_mod

        by_job: dict = {}
        for e in incumbents:
            by_job.setdefault(e.get("job") or "-", []).append({
                "t_s": round(max(0.0, e.get("ts", 0.0) - t0) / 1e6, 6),
                "best": (e.get("args") or {}).get("best"),
            })
        jobs_q = {}
        for key, pts in sorted(by_job.items()):
            pts.sort(key=lambda p: p["t_s"])
            for p in pts:
                g = quality_mod.primal_gap(p["best"], optimum)
                p["gap"] = None if g is None else round(g, 6)
            pi = quality_mod.primal_integral(pts, optimum, span_s)
            jobs_q[key] = {
                "points": pts,
                "final_best": pts[-1]["best"],
                "final_gap": pts[-1]["gap"],
                "primal_integral": None if pi is None else round(pi, 6),
            }
        quality = {
            "instance": ref_args.get("instance"),
            "optimum": optimum,
            "jobs": jobs_q,
        }

    return {
        "events": len(evts),
        "span_s": round(span_s, 6),
        "hosts": len({e.get("pid", 0) for e in evts}),
        "steal": steal,
        "steal_links": steal_links,
        "idle": idle,
        "cycle_rate": timeline,
        "device_counters": counters_total,
        "survivor_path": survivor,
        "phase_decomp": phase_decomp,
        "roofline": roofline,
        "jobs": job_lanes,
        "quality": quality,
    }


#: Human names for the phase slots (the decomposition table + the
#: "next structural cost" line use these, not the internal slugs).
_PHASE_LABELS = {
    "pop": "pop/select",
    "eval": "bound evaluation",
    "compact": "compaction",
    "push": "fused prune+push",
    "overflow": "overflow branch",
    "balance": "steal/exchange (mesh)",
    "loop": "loop overhead",
}


def phase_table(decomp: dict) -> list[str]:
    """The ``tts report`` / ``tts profile`` decomposition table: one line
    per phase (measured device ns + share of the cycle), closed by the
    dominant-phase call-out — the "measured cycle decomposition naming
    the next structural cost" deliverable of ROADMAP item 1."""
    ns = decomp.get("ns", {})
    sh = decomp.get("shares", {})
    out = ["phase decomposition (on-device cycle clocks, ns):"]
    for slot in ("pop", "eval", "compact", "push", "overflow"):
        out.append(
            f"  {_PHASE_LABELS[slot]:<22} {ns.get(slot, 0):>14,}  "
            f"{100.0 * sh.get(slot, 0.0):5.1f}% of cycle"
        )
    out.append(f"  {'cycle total':<22} {ns.get('total', 0):>14,}")
    for slot in ("balance", "loop"):
        if ns.get(slot):
            out.append(
                f"  {_PHASE_LABELS[slot]:<22} {ns.get(slot, 0):>14,}  "
                "(outside the cycle)"
            )
    if decomp.get("dominant"):
        out.append(
            f"  next structural cost: {_PHASE_LABELS[decomp['dominant']]}, "
            f"{100.0 * decomp.get('dominant_share', 0.0):.0f}% of cycle"
        )
    return out


def render(summary: dict) -> str:
    """Human-readable report text."""
    out = []
    out.append(
        f"trace: {summary['events']} events over {summary['span_s']:.3f}s "
        f"across {summary['hosts']} host(s)"
    )
    s = summary["steal"]
    if s["attempts"]:
        eff = 100.0 * s["efficiency"]
        out.append(
            f"steal efficiency: {s['successes']}/{s['attempts']} attempts "
            f"({eff:.1f}%), {s['nodes_moved']} nodes moved"
        )
    else:
        out.append("steal efficiency: no steal attempts recorded")
    out.append(
        f"inter-host: {s['exchange_rounds']} exchange round(s), "
        f"{s['interhost_blocks_sent']} block(s) / "
        f"{s['interhost_nodes_sent']} node(s) donated"
    )
    if summary.get("steal_links"):
        # Cheapest link class first — the victim-selection escalation
        # order of the hierarchical policy (parallel/topology.py).
        order = {"local": 0, "ici": 1, "dcn": 2}
        out.append("steal table per link class:")
        for link, b in sorted(summary["steal_links"].items(),
                              key=lambda kv: (order.get(kv[0], 9), kv[0])):
            mc = (f"{b['mean_cost_us']:,.0f}us"
                  if b["mean_cost_us"] is not None else "-")
            out.append(
                f"  {link:<6} attempts={b['attempts']} hits={b['hits']} "
                f"misses={b['misses']} nodes={b['nodes']} "
                f"bytes={b['bytes']} mean_cost={mc}"
            )
    out.append("idle fraction per worker:")
    if summary["idle"]:
        for key, w in summary["idle"].items():
            out.append(
                f"  {key}: idle {100.0 * w['idle_fraction']:5.1f}%  "
                f"busy {100.0 * w['busy_fraction']:5.1f}%"
            )
    else:
        out.append("  no worker tracks recorded")
    out.append("cycle-rate timeline:")
    if summary["cycle_rate"]:
        for b in summary["cycle_rate"]:
            out.append(
                f"  t={b['t_s']:8.3f}s  {b['cycles_per_sec']:12.1f} cyc/s  "
                f"{b['nodes_per_sec']:14.1f} nodes/s  "
                f"({b['dispatches']} dispatch(es))"
            )
    else:
        out.append("  no dispatch events recorded")
    if summary["device_counters"]:
        c = summary["device_counters"]
        out.append(
            "device counters: "
            + "  ".join(f"{k}={v}" for k, v in sorted(c.items()))
        )
    if summary.get("phase_decomp"):
        out.extend(phase_table(summary["phase_decomp"]))
    if summary.get("roofline"):
        from . import roofline as roofline_mod

        out.extend(roofline_mod.table(summary["roofline"]))
    if summary.get("survivor_path"):
        sp = summary["survivor_path"]
        out.append(
            f"survivor path: {sp['eval_rows']} child evals vs "
            f"{sp['push_rows']} push rows"
            + (f" ({sp['push_rows_per_survivor']} rows/survivor)"
               if sp["push_rows_per_survivor"] is not None else "")
            + f", {sp['overflow_cycles']} overflow cycle(s)"
        )
    if summary.get("jobs"):
        out.append("per-job lanes:")
        for j, info in summary["jobs"].items():
            out.append(
                f"  {j}: {info['events']} event(s), "
                f"{info['dispatches']} dispatch(es) over "
                f"{info['span_s']:.3f}s"
                + (f", best={info['best']}"
                   if info["best"] is not None else "")
                + (f", slot {'/'.join(str(s) for s in info['slots'])}"
                   f" of B={info['batch_width']}"
                   if info.get("slots") else "")
            )
    if summary.get("quality"):
        q = summary["quality"]
        head = "quality vs time"
        if q.get("instance") and q.get("optimum") is not None:
            head += f" (instance {q['instance']}, optimum {q['optimum']})"
        out.append(head + ":")
        for key, jq in q["jobs"].items():
            label = "" if key == "-" else f"{key}: "
            for p in jq["points"]:
                gap = ("gap ?" if p["gap"] is None
                       else f"gap {100.0 * p['gap']:6.2f}%")
                out.append(
                    f"  {label}t={p['t_s']:8.3f}s  best={p['best']}  {gap}"
                )
            tail = []
            if jq["final_gap"] is not None:
                tail.append(f"final gap {100.0 * jq['final_gap']:.2f}%")
            if jq["primal_integral"] is not None:
                tail.append(f"primal integral {jq['primal_integral']:.4f}")
            if tail:
                out.append(f"  {label}" + ", ".join(tail))
    return "\n".join(out)


def report_main(trace_paths, as_json: bool = False,
                roofline: bool = False,
                costmodel: str | None = None) -> int:
    """The ``tts report`` entry point.

    Accepts one or many files — traces, metrics JSONL, flight-recorder
    dumps — merged into a single report (multi-worker sessions write one
    metrics file per host; the union is the honest whole-run view).
    Robustness contract: a truncated or empty file is summarized as far
    as it parses, with a warning on stderr and exit 0 — a post-mortem
    artifact from a killed run must never be unreadable by its own
    tooling. Exit 2 only when NO input could be read at all.

    ``roofline=True`` (the ``--roofline`` flag) makes the memory-roofline
    section mandatory: exit 2 with a diagnostic when the trace lacks the
    phase splits / cycle counts / ``roofline_meta`` facts it needs.
    ``costmodel`` optionally names a COSTMODEL.json whose measured ``hbm``
    link fit supplies the peak-bandwidth denominator."""
    import sys

    from .export import load_trace_lenient

    if isinstance(trace_paths, str):
        trace_paths = [trace_paths]
    profile = None
    if costmodel:
        from . import costmodel as CM

        profile = CM.load(costmodel)
        if profile is None:
            # An explicitly named profile that cannot be read is an
            # operator error here (unlike the controllers' soft fallback).
            print(f"Error: cannot load cost model {costmodel!r}",
                  file=sys.stderr)
            return 2
    evts: list[dict] = []
    readable = 0
    for path in trace_paths:
        try:
            part, warn = load_trace_lenient(path)
        except OSError as e:
            print(f"Error: cannot read {path!r}: {e}", file=sys.stderr)
            continue
        readable += 1
        if warn:
            print(f"Warning: {warn}", file=sys.stderr)
        evts.extend(part)
    if not readable:
        return 2
    if not evts:
        print("Warning: no events recovered from "
              f"{len(trace_paths)} file(s); reporting empty summary",
              file=sys.stderr)
    evts.sort(key=lambda e: e.get("ts", 0.0))
    summary = summarize(evts, costmodel=profile)
    if roofline and not summary.get("roofline"):
        print(
            "Error: --roofline needs a phase-profiled trace "
            "(TTS_PHASEPROF=1 run with dispatch cycle counts and a "
            "roofline_meta event); none of the inputs carry one",
            file=sys.stderr,
        )
        return 2
    try:
        if as_json:
            print(json.dumps(summary))
        else:
            print(render(summary))
    except BrokenPipeError:
        # `tts report t.json | head` closing the pipe is not an error.
        import os
        import sys

        try:
            sys.stdout.close()
        except Exception:
            os._exit(0)
    return 0
