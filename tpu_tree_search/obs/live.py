"""``--obs-serve`` / ``tts watch`` — live telemetry streaming.

A zero-dependency localhost HTTP endpoint over the flight recorder's
snapshot ring (stdlib ``http.server`` in a daemon thread), plus the
``tts watch`` client. This is the streaming-progress seed of the
search-as-a-service direction (ROADMAP item 2, arXiv:2002.07062): the
same snapshots a resident server would push to its tenants.

Endpoints (``127.0.0.1`` only — this is an operator console, not a
service surface):

  * ``GET /snapshot``      — the latest snapshot as one JSON object
    (``{}`` until the first dispatch boundary lands);
  * ``GET /snapshots?n=K`` — the most recent K ring snapshots (JSON
    array; whole ring without ``n``);
  * ``GET /state``         — the flight recorder's post-mortem payload
    (last dispatch per worker, idle map, run meta) — live;
  * ``GET /stream``        — Server-Sent Events: one ``data:`` line per
    new snapshot (~the heartbeat cadence, rate-limited at the source);
  * ``GET /healthz``       — liveness probe.

Server cost model: snapshots are produced by the engines' existing
dispatch-boundary heartbeats whether or not anyone listens; serving them
reads the ring under its lock. Nothing here touches device programs or
the dispatch path — ``--obs-serve`` on a guarded run stays green.
"""

from __future__ import annotations

import json
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from . import flightrec

#: SSE poll cadence: the ring refreshes at most every
#: ``flightrec.SNAPSHOT_PERIOD_US``; polling faster only burns cycles.
STREAM_POLL_S = 0.2


class _Handler(BaseHTTPRequestHandler):
    server_version = "tts-obs/1"

    def log_message(self, fmt, *args):  # silence per-request stderr noise
        pass

    def _json(self, payload, code: int = 200) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler's contract
        url = urlparse(self.path)
        try:
            if url.path == "/snapshot":
                self._json(flightrec.latest() or {})
            elif url.path == "/snapshots":
                q = parse_qs(url.query)
                n = None
                if "n" in q:
                    try:
                        n = max(1, int(q["n"][0]))
                    except ValueError:
                        n = None
                self._json(flightrec.snapshots(n))
            elif url.path == "/state":
                self._json(flightrec.recorder().state())
            elif url.path == "/healthz":
                self._json({"ok": True})
            elif url.path == "/stream":
                self._stream()
            else:
                self._json({"error": "unknown path"}, code=404)
        except (BrokenPipeError, ConnectionResetError):
            pass  # client went away; nothing to clean up

    def _stream(self) -> None:
        sse_begin(self)
        stream_snapshots(
            self, flightrec.latest,
            stop_fn=lambda: getattr(self.server, "closing", False),
        )


# -- SSE plumbing (shared with the serve daemon's per-job streams) ----------


def sse_begin(handler: BaseHTTPRequestHandler, comment: str = "tts snapshot stream") -> None:
    """Open a Server-Sent-Events response on ``handler``."""
    handler.send_response(200)
    handler.send_header("Content-Type", "text/event-stream")
    handler.send_header("Cache-Control", "no-cache")
    handler.end_headers()
    handler.wfile.write(b": " + comment.encode() + b"\n\n")
    handler.wfile.flush()


def sse_event(handler: BaseHTTPRequestHandler, payload: dict,
              event: str | None = None) -> None:
    """One SSE frame (optionally named via ``event:``)."""
    buf = b""
    if event:
        buf += b"event: " + event.encode() + b"\n"
    buf += b"data: " + json.dumps(payload).encode() + b"\n\n"
    handler.wfile.write(buf)
    handler.wfile.flush()


def stream_snapshots(handler: BaseHTTPRequestHandler, latest_fn,
                     stop_fn=None, poll_s: float = STREAM_POLL_S,
                     final_fn=None, events_fn=None) -> None:
    """Poll ``latest_fn()`` and push each NEW snapshot (by ``ts_us``) as an
    SSE frame until ``stop_fn()`` goes true. ``final_fn()`` (optional) may
    return one terminal payload, sent as an ``event: done`` frame — the
    serve daemon closes a finished job's stream with its result record so
    a client needs no second round trip. ``events_fn()`` (optional) may
    return a list of ``(event_name, payload)`` extra frames, drained every
    poll AND once more before the ``done`` frame — the serve daemon uses
    it for ``event: incumbent`` quality frames, and the final drain
    guarantees every incumbent recorded during the run is on the wire
    before the stream closes."""
    last_ts = None

    def push_new() -> None:
        nonlocal last_ts
        if events_fn is not None:
            for name, payload in events_fn():
                sse_event(handler, payload, event=name)
        snap = latest_fn()
        if snap is not None and snap.get("ts_us") != last_ts:
            last_ts = snap.get("ts_us")
            sse_event(handler, snap)

    while not (stop_fn is not None and stop_fn()):
        push_new()
        time.sleep(poll_s)
    # Flush the frame that may have landed during the last sleep — a fast
    # job's only snapshot must not lose the race with its own completion.
    push_new()
    if final_fn is not None:
        payload = final_fn()
        if payload is not None:
            sse_event(handler, payload, event="done")


def iter_sse(resp):
    """Client side: yield ``(event, payload)`` per SSE frame from an open
    ``urlopen`` response (``event`` is None for plain ``data:`` frames;
    unparseable frames are skipped)."""
    event = None
    for raw in resp:
        line = raw.decode(errors="replace").strip()
        if line.startswith("event: "):
            event = line[len("event: "):]
            continue
        if not line.startswith("data: "):
            if not line:
                event = None  # frame boundary
            continue
        try:
            payload = json.loads(line[len("data: "):])
        except ValueError:
            continue
        yield event, payload
        event = None


class LiveServer:
    """The ``--obs-serve`` server handle: ``port`` is the bound port
    (pass 0 to let the OS pick — tests do), ``close()`` stops serving."""

    def __init__(self, port: int, host: str = "127.0.0.1"):
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.closing = False
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.2},
            name="tts-obs-serve", daemon=True,
        )
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def close(self) -> None:
        self._httpd.closing = True
        self._httpd.shutdown()
        self._httpd.server_close()


def serve(port: int, host: str = "127.0.0.1") -> LiveServer:
    """Start the live monitor (daemon thread; returns immediately)."""
    return LiveServer(port, host)


# -- the `tts watch` client --------------------------------------------------


def format_snapshot(snap: dict) -> str:
    """One human status line from a snapshot (the watch display unit)."""
    if not snap:
        return "waiting for first snapshot..."
    best = snap.get("best")
    size = snap.get("size")
    parts = [
        f"[{snap.get('tier', '?')}]",
        f"{snap.get('nodes_per_sec', 0.0):>12,.0f} nodes/s",
        f"best={best if best is not None else '-'}",
        f"pool={size if size is not None else '-'}",
        f"depth={snap.get('depth', 1)}",
        f"K={snap.get('K') if snap.get('K') is not None else '-'}",
    ]
    if snap.get("workers", 0) > 1:
        parts.append(
            f"workers={snap['workers']}"
            f"(idle {snap.get('idle_workers', 0)})"
        )
    if snap.get("steals"):
        parts.append(f"steals={snap['steals']}")
    if snap.get("steal_link"):
        # Hierarchical stealing (TTS_STEAL=hier): which link class last
        # fed this run — on a stall, the level the search was living off.
        lvl = snap.get("steal_level")
        parts.append(
            f"steal={snap['steal_link']}"
            + (f"/L{lvl}" if lvl is not None else "")
        )
    if snap.get("dominant_phase"):
        # TTS_PHASEPROF runs: where the last dispatch spent its cycles.
        share = snap.get("dominant_phase_share", 0.0)
        parts.append(f"phase={snap['dominant_phase']}:{100.0 * share:.0f}%")
    parts.append(f"dispatch#{snap.get('seq', 0)}")
    return "  ".join(parts)


def _fetch_json(url: str, timeout: float = 5.0):
    from urllib.request import urlopen

    with urlopen(url, timeout=timeout) as resp:  # noqa: S310 — localhost
        return json.loads(resp.read().decode())


def watch_main(port: int, host: str = "127.0.0.1", interval: float = 1.0,
               once: bool = False, as_json: bool = False,
               max_updates: int | None = None) -> int:
    """``tts watch`` entry point: stream (SSE) with a polling fallback.

    ``once`` prints the current snapshot and exits; ``max_updates`` bounds
    a streaming session (tests; unbounded for operators, ^C to stop).
    Returns 0 on success, 2 when the monitor is unreachable.
    """
    base = f"http://{host}:{port}"
    emit = (lambda s: print(json.dumps(s), flush=True)) if as_json else (
        lambda s: print(format_snapshot(s), flush=True)
    )
    if once:
        try:
            snap = _fetch_json(base + "/snapshot")
        except OSError as e:
            print(f"Error: no live monitor at {base}: {e}", file=sys.stderr)
            return 2
        emit(snap)
        return 0
    from urllib.request import urlopen

    seen = 0
    last_ts = None  # carried into the fallback: no duplicate reprint
    try:
        try:
            with urlopen(base + "/stream", timeout=30.0) as resp:  # noqa: S310
                for _event, snap in iter_sse(resp):
                    emit(snap)
                    seen += 1
                    last_ts = snap.get("ts_us", last_ts)
                    if max_updates is not None and seen >= max_updates:
                        return 0
        except OSError as e:
            if seen == 0 and not _poll_ok(base):
                print(f"Error: no live monitor at {base}: {e}",
                      file=sys.stderr)
                return 2
        # Stream dropped (run over or timeout): fall back to polling until
        # the server goes away entirely.
        while max_updates is None or seen < max_updates:
            try:
                snap = _fetch_json(base + "/snapshot")
            except OSError:
                return 0 if seen else 2
            if snap and snap.get("ts_us") != last_ts:
                last_ts = snap.get("ts_us")
                emit(snap)
                seen += 1
            time.sleep(interval)
    except KeyboardInterrupt:
        pass
    return 0


def _poll_ok(base: str) -> bool:
    try:
        _fetch_json(base + "/healthz", timeout=2.0)
        return True
    except OSError:
        return False
