"""Benchmark harness — prints ONE JSON line for the driver.

Headline metric (BASELINE.json): nodes/sec/chip on PFSP ta014 (lb1, ub=1,
single device) = exploredTree / device-phase seconds, with strict makespan
parity (1377) and tree/sol parity against the reference C implementation
(tree 2573652, sol 2648 — recorded goldens, see tests/test_sequential.py).

The reference publishes no in-repo numbers (`published: {}` in
BASELINE.json), so ``vs_baseline`` is reported against REFERENCE_NODES_PER_SEC
below — the first recorded value of this same benchmark on this hardware
(round 1); later rounds show relative progress.

Engine: the device-resident tier (pool in HBM, chunk cycles inside one
jitted while-loop) — ~10x the classic host-offload loop on remote-TPU
runtimes because it removes the per-chunk host round trip.

Runs on whatever platform jax picks (real TPU under the driver). Set
JAX_PLATFORMS=cpu to smoke-test on CPU.
"""

from __future__ import annotations

import json
import sys
import time

# Self-anchored baseline: round-1 recorded nodes/sec of this benchmark on the
# v5e chip (the reference repo publishes no numbers to compare against).
REFERENCE_NODES_PER_SEC = 100_000.0

GOLDEN = {"tree": 2_573_652, "sol": 2648, "makespan": 1377}


def main() -> int:
    from tpu_tree_search.cli import enable_compile_cache

    enable_compile_cache()

    from tpu_tree_search.engine.resident import resident_search
    from tpu_tree_search.problems import PFSPProblem

    problem = PFSPProblem(inst=14, lb="lb1", ub=1)

    # Throwaway warm-up search compiles the device-resident while-loop
    # program (~30s first time on TPU); the measured run below reflects
    # steady-state throughput.
    resident_search(problem, m=25, M=65536)

    t0 = time.time()
    res = resident_search(problem, m=25, M=65536)
    elapsed = time.time() - t0

    device_phase = res.phases[1].seconds if len(res.phases) > 1 else res.elapsed
    nodes_per_sec = res.explored_tree / max(device_phase, 1e-9)

    parity = (
        res.explored_tree == GOLDEN["tree"]
        and res.explored_sol == GOLDEN["sol"]
        and res.best == GOLDEN["makespan"]
    )
    record = {
        "metric": "pfsp_ta014_lb1_nodes_per_sec_per_chip",
        "value": round(nodes_per_sec, 1),
        "unit": "nodes/sec",
        "vs_baseline": round(nodes_per_sec / REFERENCE_NODES_PER_SEC, 3),
        "parity": parity,
        "explored_tree": res.explored_tree,
        "explored_sol": res.explored_sol,
        "makespan": res.best,
        "device_phase_s": round(device_phase, 3),
        "total_s": round(elapsed, 3),
        "kernel_launches": res.diagnostics.kernel_launches,
    }
    print(json.dumps(record))
    return 0 if parity else 1


if __name__ == "__main__":
    sys.exit(main())
