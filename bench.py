"""Benchmark harness — prints ONE JSON line for the driver.

Headline metric (BASELINE.json): nodes/sec/chip on PFSP ta014 (lb1, ub=1,
single device) = exploredTree / device-phase seconds, with strict makespan
parity (1377) and tree/sol parity against the reference C implementation
(tree 2573652, sol 2648 — recorded goldens, see tests/test_sequential.py).
Extra records (same JSON line): PFSP ta014 lb2 (tree 144639, sol 0) and
N-Queens N=15 (sol 2279184) — BASELINE.md configs 2/4 anchors.

``vs_baseline`` is measured against REFERENCE_NODES_PER_SEC below: the first
*recorded* value of this benchmark on this hardware — 1,414,503 nodes/s,
verified on the real v5e chip in the round-2 review (`TTS_PALLAS=0
python bench.py`). The reference repo publishes no in-repo numbers
(`published: {}` in BASELINE.json), so this self-anchor shows relative
progress across rounds; the *external* anchors are ``vs_ref_c_seq`` /
``vs_ref_c_lb1d`` — the reference's own C sequential programs measured on
this host (REF_C_SEQ below, BASELINE.md).

Robustness (the reference always emits its stats line,
`pfsp_gpu_cuda.c:140-148` — so must we): the Pallas kernels are probed in a
SUBPROCESS with a timeout first; if the probe crashes, hangs, or
mismatches the jnp oracle, the whole bench runs with ``TTS_PALLAS=0`` (the
jnp/XLA path) and records ``pallas: false`` plus the error. A kernel
regression can cost performance, never the round's number.

Runs on whatever platform jax picks (real TPU under the driver). Set
JAX_PLATFORMS=cpu to smoke-test on CPU.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

# Self-anchored baseline: first recorded nodes/sec of the headline benchmark
# on the v5e chip (round-2 review, jnp path — see module docstring).
REFERENCE_NODES_PER_SEC = 1_414_503.0

# External, non-circular anchors: the reference's own C sequential programs
# (`baselines/pfsp/pfsp_c.c`, `baselines/nqueens/nqueens_c.c`) built with
# gcc -O3 and measured on this host's Xeon @2.10GHz, single core, best of 3
# with full tree/sol/makespan parity (see BASELINE.md "Measured reference C
# sequential baselines"). The headline ratio ``vs_ref_c_seq`` divides by the
# same-bound-variant anchor; ``vs_ref_c_lb1d`` uses the reference's fastest
# CPU formulation of the same tree (lb1_d) as a second honesty anchor.
REF_C_SEQ = {
    "pfsp_ta014_lb1": 927_909.0,
    "pfsp_ta014_lb1_d": 3_899_473.0,
    "pfsp_ta014_lb2": 65_391.0,
    "nqueens_n14": 10_471_617.0,
    "nqueens_n15": 9_942_907.0,
}

GOLDEN_LB1 = {"tree": 2_573_652, "sol": 2648, "makespan": 1377}
GOLDEN_LB2 = {"tree": 144_639, "sol": 0, "makespan": 1377}
# Classical N-Queens solution counts (BASELINE.md correctness anchors).
NQ_SOL = {12: 14_200, 14: 365_596, 15: 2_279_184}

# Last successful on-chip measurement, committed so a tunnel outage degrades
# the round's artifact to "stale number" instead of "no number" (three rounds
# lost their value to env failures before this existed).
LAST_GOOD_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "BENCH_LAST_GOOD.json")

# TPU v5e (v5 lite) MXU peak — the roofline denominator. bf16 x bf16 -> f32
# is the kernels' matmul mode (exact for the <2^8 one-hot/time operands).
V5E_PEAK_BF16_TFLOPS = 197.0

# Headline chunk size, measured on the real v5e (scripts/headline_tune.py,
# round 5): per-cycle cost is ~linear in M (dense padded compute) while the
# ta014 frontier rarely fills large chunks, so small-but-full chunks win —
# M=1024 ran 1.87M nodes/s vs 1.46M at the old 65536 (28% on the same tree;
# 512 underutilizes, the 1024-8192 plateau is flat within ~3%).
HEADLINE_M = 1024


def flops_per_parent_model(n: int, m: int, P: int | None, lb: str) -> float:
    """Hand-counted FLOPs per explored parent of the jnp evaluators — the
    fallback when XLA cost analysis is unavailable, cross-checked against it
    by ``tests/test_bench.py``. lb1 = two (n, n) x (n, m) one-hot gathers
    (2 * 2n^2m) plus the O(nm) scan and the m-chain over n children (~6nm);
    lb2 adds, per machine pair, one (n, n) one-hot reorder contraction
    (2n^2) and the O(n) closed-form Johnson scan (~8n) — NOT per-pair
    (n, n) x (n, n) matmuls; the implementation is O(P n^2), which the
    round-5 cost-analysis cross-check confirmed (the earlier 6n^3-per-pair
    model overstated lb2 work ~67x)."""
    if lb == "lb2":
        return (P or 0) * (2.0 * n**2 + 8.0 * n) + 4.0 * n**2 * m
    return 4.0 * n**2 * m + 6.0 * n * m


def flops_per_parent_xla(problem, lb: str, batch: int = 64) -> float | None:
    """Compiler-measured FLOPs per parent: lower + compile the jnp chunk
    evaluator for the current backend and read XLA's cost analysis. This is
    the authoritative roofline numerator — it counts what the compiled
    program executes, not what a hand model assumes. Returns None when cost
    analysis is unavailable (some backends) or the compile fails; callers
    fall back to ``flops_per_parent_model``. The Pallas kernels do the same
    semantic work with the same asymptotics (XLA cannot see inside a custom
    call), so the jnp figure stands in for both paths."""
    cache = getattr(problem, "_flops_per_parent_xla", None)
    if cache is None:
        cache = problem._flops_per_parent_xla = {}
    if lb in cache:
        return cache[lb]
    try:
        import jax.numpy as jnp
        import numpy as np

        from tpu_tree_search.ops import pfsp_device as P

        t = problem.device_tables()
        n = problem.jobs
        prmu = jnp.asarray(
            np.tile(np.arange(n, dtype=np.int32), (batch, 1))
        )
        limit1 = jnp.zeros((batch,), dtype=jnp.int32)
        # Lower the module-level jits with the tables as RUNTIME arguments —
        # exactly how production calls them. A wrapper closure would bake
        # the tables in as HLO constants and cost-analyse a
        # differently-folded program.
        if lb == "lb2":
            lowered = P._lb2_chunk.lower(
                prmu, limit1, t.ptm_t, t.min_heads, t.min_tails, t.pairs,
                t.lags, t.johnson_schedules, bf16=t.exact_bf16,
            )
        else:
            lowered = P._lb1_chunk.lower(
                prmu, limit1, t.ptm_t, t.min_heads, t.min_tails,
                bf16=t.exact_bf16,
            )
        ca = lowered.compile().cost_analysis()
        flops = float(ca.get("flops", 0.0)) if ca else 0.0
        cache[lb] = flops / batch if flops > 0 else None
    except Exception:
        cache[lb] = None
    return cache[lb]


def roofline(nps: float, n: int, m: int, P: int | None, lb: str,
             problem=None) -> dict:
    """Achieved-work roofline for the headline run. ``nps`` counts explored
    parents/sec; every explored parent evaluates all n children in one
    evaluator pass, so bound-evals/sec = nps * n. FLOPs/parent comes from
    XLA cost analysis of the compiled jnp evaluator when ``problem`` is
    given (``flop_source: xla_cost_analysis``), else the hand model.
    ``mfu_pct`` is achieved-FLOPs / bf16 MXU peak — honest MFU for a
    branch-and-bound workload whose useful work is bounds, not FLOPs."""
    measured = flops_per_parent_xla(problem, lb) if problem is not None else None
    flops_per_parent = (
        measured if measured is not None
        else flops_per_parent_model(n, m, P, lb)
    )
    gflops = nps * flops_per_parent / 1e9
    return {
        "bound_evals_per_sec": round(nps * n, 1),
        "flops_per_parent": int(flops_per_parent),
        "flop_source": "xla_cost_analysis" if measured is not None else "model",
        "achieved_gflops": round(gflops, 2),
        "peak_bf16_tflops": V5E_PEAK_BF16_TFLOPS,
        "mfu_pct": round(100.0 * gflops / (V5E_PEAK_BF16_TFLOPS * 1e3), 4),
    }


def contracts_fingerprint() -> str | None:
    """The committed compiled-program contract fingerprint
    (`.tts-contracts.json`, ISSUE 8): recorded in every bench artifact so
    a banked perf number is tied to the exact program STRUCTURE it
    measured — a later `tts check --update` (reviewed drift) makes old
    rows distinguishable from new ones at a glance."""
    try:
        from tpu_tree_search.analysis.program_audit import (
            committed_fingerprint,
        )

        return committed_fingerprint(os.path.join(
            os.path.dirname(os.path.abspath(__file__)), ".tts-contracts.json"
        ))
    except Exception:  # noqa: BLE001 — provenance must never break a row
        return None


def _git_head() -> str:
    try:
        out = subprocess.run(
            ["git", "-C", os.path.dirname(os.path.abspath(__file__)),
             "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10,
        )
        return out.stdout.strip() or "unknown"
    except Exception:
        return "unknown"


def record_last_good(record: dict) -> None:
    """Persist the measurement so later outage records can cite it."""
    try:
        with open(LAST_GOOD_PATH, "w") as f:
            json.dump({
                "metric": record["metric"],
                "value": record["value"],
                "vs_baseline": record["vs_baseline"],
                "vs_ref_c_seq": record.get("vs_ref_c_seq"),
                "pallas": record.get("pallas", False),
                "compact": record.get("compact", {}).get("picked"),
                "contracts": record.get("contracts"),
                "commit": _git_head(),
                "date": time.strftime("%Y-%m-%d %H:%M:%S UTC", time.gmtime()),
            }, f, indent=1)
    except OSError:
        pass  # never let bookkeeping break the bench line


def last_good() -> dict | None:
    try:
        with open(LAST_GOOD_PATH) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


# Failure-provenance artifact (flush-as-you-go): three rounds produced an
# EMPTY bench trajectory because the one JSON line only prints at the very
# end and dead-tunnel sessions never got there. This file is rewritten
# (atomic replace + fsync) after every stage, so whatever already ran is
# on disk when the process dies — rc, per-stage/per-row status, and the
# failure reason included. TTS_BENCH_PARTIAL overrides the path; =0
# disables.
PARTIAL_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_PARTIAL.json")


class BenchPartial:
    """Crash-durable per-stage bench status (see PARTIAL_PATH note)."""

    def __init__(self, path: str | None = None):
        raw = os.environ.get("TTS_BENCH_PARTIAL", "")
        if raw == "0":
            default = None
        elif os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
            # CPU smoke (JAX_PLATFORMS=cpu pins, incl. the express e2e
            # test) must not dirty the working tree — same policy as
            # BENCH_TRACE.json; hardware runs keep the committed path.
            import tempfile

            default = os.path.join(tempfile.gettempdir(),
                                   "BENCH_PARTIAL.json")
        else:
            default = PARTIAL_PATH
        self.path = None if raw == "0" else (path or raw or default)
        self.doc = {
            "status": "running",
            "rc": None,
            "started": time.strftime("%Y-%m-%d %H:%M:%S UTC", time.gmtime()),
            "commit": _git_head(),
            # Program-structure provenance: the committed contract
            # fingerprint every row in this document was measured under.
            "contracts": contracts_fingerprint(),
            "rows": [],
        }
        self._index: dict[str, int] = {}
        self._prev_sigterm = None
        self.write()

    def stage(self, name: str, status: str = "ok", **info) -> None:
        row = {"stage": name, "status": status, **info}
        i = self._index.get(name)
        if i is None:
            self._index[name] = len(self.doc["rows"])
            self.doc["rows"].append(row)
        else:
            self.doc["rows"][i] = row
        self.write()

    def rows_from_extras(self, extras: list[dict]) -> None:
        for rec in extras:
            name = rec.get("metric", "extra")
            self.stage(
                name,
                "error" if "error" in rec else "ok",
                **({"error": rec["error"]} if "error" in rec
                   else {"value": rec.get("value")}),
            )

    def finish(self, rc: int, status: str = "complete") -> None:
        self.doc["status"] = status
        self.doc["rc"] = rc
        self.write()

    def write(self) -> None:
        if self.path is None:
            return
        try:
            self.doc["updated"] = time.strftime(
                "%Y-%m-%d %H:%M:%S UTC", time.gmtime()
            )
            tmp = self.path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(self.doc, f, indent=1)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
        except OSError:
            pass  # provenance must never break the bench itself

    def install_sigterm(self) -> None:
        """SIGTERM (the driver's timeout kill) marks the partial before
        the process dies with the honest signal status."""
        import signal
        import threading

        if threading.current_thread() is not threading.main_thread():
            return

        def _on_term(signum, frame):
            self.finish(128 + signum, "killed: SIGTERM")
            signal.signal(signum, self._prev_sigterm or signal.SIG_DFL)
            os.kill(os.getpid(), signum)

        try:
            self._prev_sigterm = signal.signal(signal.SIGTERM, _on_term)
        except (ValueError, OSError):
            pass

# lb1-family probe (lb1 + nqueens): these kernels are hardware-proven
# (docs/HW_VALIDATION.md) and carry the HEADLINE metric. Probed separately
# from lb2 so an lb2 compile hang/crash can never cost the lb1 Pallas path
# (one shared subprocess would flip the whole bench to jnp).
_PROBE = r"""
import sys
import numpy as np, jax
if jax.default_backend() != "tpu":
    print("PALLAS_PROBE_SKIP:" + jax.default_backend())
    sys.exit(0)
import jax.numpy as jnp
from tpu_tree_search.ops import pfsp_device as P, pallas_kernels as PK
from tpu_tree_search.problems import PFSPProblem
prob = PFSPProblem(inst=14, lb="lb1", ub=1)
t = P.PFSPDeviceTables(prob.lb1_data, prob.lb2_data)
rng = np.random.default_rng(0)
B = 256
prmu = np.tile(np.arange(prob.jobs, dtype=np.int32), (B, 1))
for i in range(B):
    rng.shuffle(prmu[i])
limit1 = rng.integers(-1, prob.jobs - 1, size=B).astype(np.int32)
pd, ld = jnp.asarray(prmu), jnp.asarray(limit1)
open_ = np.arange(prob.jobs)[None, :] >= (limit1[:, None] + 1)
g1 = np.asarray(PK.pfsp_lb1_bounds(pd, ld, t.ptm_t, t.min_heads, t.min_tails))
r1 = np.asarray(P._lb1_chunk(pd, ld, t.ptm_t, t.min_heads, t.min_tails))
assert np.array_equal(g1[open_], r1[open_]), "lb1 mismatch"
from tpu_tree_search.ops import nqueens_device as NQ
board = np.tile(np.arange(15, dtype=np.uint8), (B, 1))
for i in range(B):
    rng.shuffle(board[i])
depth = rng.integers(0, 15, size=B).astype(np.int32)
gq = np.asarray(PK.nqueens_labels(jnp.asarray(board), jnp.asarray(depth), 15))
rq = np.asarray(NQ.make_core(15)(jnp.asarray(board), jnp.asarray(depth)))
assert np.array_equal(gq, rq), "nqueens mismatch"
print("PALLAS_PROBE_OK")
"""

# lb2 child-kernel probe: its own subprocess — the biggest kernel, the one
# whose Mosaic compile is still hardware-unvalidated; a failure here routes
# only the lb2 family to jnp (TTS_PALLAS_LB2=0).
_PROBE_LB2 = r"""
import sys
import numpy as np, jax
if jax.default_backend() != "tpu":
    print("PALLAS_PROBE_SKIP:" + jax.default_backend())
    sys.exit(0)
import jax.numpy as jnp
from tpu_tree_search.ops import pfsp_device as P, pallas_kernels as PK
from tpu_tree_search.problems import PFSPProblem
prob = PFSPProblem(inst=14, lb="lb2", ub=1)
t = P.PFSPDeviceTables(prob.lb1_data, prob.lb2_data)
rng = np.random.default_rng(0)
B = 256
prmu = np.tile(np.arange(prob.jobs, dtype=np.int32), (B, 1))
for i in range(B):
    rng.shuffle(prmu[i])
limit1 = rng.integers(-1, prob.jobs - 1, size=B).astype(np.int32)
pd, ld = jnp.asarray(prmu), jnp.asarray(limit1)
open_ = np.arange(prob.jobs)[None, :] >= (limit1[:, None] + 1)
g2 = np.asarray(PK.pfsp_lb2_bounds(pd, ld, t))
r2 = np.asarray(P._lb2_chunk(pd, ld, t.ptm_t, t.min_heads, t.min_tails,
                             t.pairs, t.lags, t.johnson_schedules))
assert np.array_equal(g2[open_], r2[open_]), "lb2 mismatch"
print("PALLAS_LB2_OK")
"""

# The staged-lb2 self kernel probes in its OWN subprocess: a compile hang or
# compiler crash here must only cost the staging (TTS_LB2_STAGED=0), never
# the whole Pallas path — an in-process try/except cannot catch either
# failure mode.
_PROBE_STAGED = r"""
import sys
import numpy as np, jax
if jax.default_backend() != "tpu":
    print("PALLAS_PROBE_SKIP:" + jax.default_backend())
    sys.exit(0)
import jax.numpy as jnp
from tpu_tree_search.ops import pfsp_device as P, pallas_kernels as PK
from tpu_tree_search.problems import PFSPProblem
prob = PFSPProblem(inst=14, lb="lb2", ub=1)
t = P.PFSPDeviceTables(prob.lb1_data, prob.lb2_data)
rng = np.random.default_rng(0)
B = 256
prmu = np.tile(np.arange(prob.jobs, dtype=np.int32), (B, 1))
for i in range(B):
    rng.shuffle(prmu[i])
limit1 = rng.integers(0, prob.jobs - 1, size=B).astype(np.int32)
pd, ld = jnp.asarray(prmu), jnp.asarray(limit1)
gs = np.asarray(PK.pfsp_lb2_self_bounds(pd, ld, B, t))
rs = np.asarray(P._lb2_self_chunk(
    pd, ld, t.ptm_t, t.min_heads, t.min_tails,
    t.pairs, t.lags, t.johnson_schedules))
assert np.array_equal(gs, rs), "lb2_self mismatch"
print("PALLAS_STAGED_OK")
"""


# Goldens are substituted from GOLDEN_LB1/GOLDEN_LB2/NQ_SOL below (one
# source of truth; a count correction must not silently fail parity here).
# Each workload streams its own flushed HOST_SEQ_ROW line so measurements
# that finished before a timeout/crash still get banked.
_HOST_SEQ = r"""
import json, os, time
# Unconditional CPU pin: the host-seq measurement must run during TPU
# outages (a non-empty inherited PALLAS_AXON_POOL_IPS would hang jax
# backend init — the whole point is to bank numbers when that happens).
os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["JAX_PLATFORMS"] = "cpu"
from tpu_tree_search.engine import sequential_search
from tpu_tree_search.problems import NQueensProblem, PFSPProblem
for tag, mk, tree, sol, best in (
    ("pfsp_ta014_lb1", lambda: PFSPProblem(inst=14, lb="lb1", ub=1),
     @LB1_TREE@, @LB1_SOL@, @LB1_MS@),
    ("pfsp_ta014_lb2", lambda: PFSPProblem(inst=14, lb="lb2", ub=1),
     @LB2_TREE@, @LB2_SOL@, @LB2_MS@),
    ("nqueens_n14", lambda: NQueensProblem(N=14), 27358552, @NQ14_SOL@,
     None),
):
    bnps = None
    parity = True
    for _ in range(2):
        t0 = time.time()
        r = sequential_search(mk())
        dt = time.time() - t0
        parity &= (r.explored_tree, r.explored_sol) == (tree, sol)
        if best is not None:
            parity &= r.best == best
        nps = r.explored_tree / max(dt, 1e-9)
        bnps = nps if bnps is None else max(bnps, nps)
    print("HOST_SEQ_ROW " + json.dumps(
        {"tag": tag, "nodes_per_sec": round(bnps, 1), "parity": parity}
    ), flush=True)
""".replace("@LB1_TREE@", str(GOLDEN_LB1["tree"])) \
   .replace("@LB1_SOL@", str(GOLDEN_LB1["sol"])) \
   .replace("@LB1_MS@", str(GOLDEN_LB1["makespan"])) \
   .replace("@LB2_TREE@", str(GOLDEN_LB2["tree"])) \
   .replace("@LB2_SOL@", str(GOLDEN_LB2["sol"])) \
   .replace("@LB2_MS@", str(GOLDEN_LB2["makespan"])) \
   .replace("@NQ14_SOL@", str(NQ_SOL[14]))


def host_seq_extras(timeout_s: float = 180.0) -> list[dict]:
    """Measured host-runtime (C++ sequential tier) records with ratios
    against the reference C programs (BASELINE.md) — these need no TPU, so
    even an outage round's artifact carries real numbers. Subprocess +
    timeout; NEVER raises: a native-runtime crash, a timeout, or garbled
    output must cost only this block, not the bench's JSON line (rows
    already streamed before the failure are kept)."""
    try:
        err = None
        try:
            res = subprocess.run(
                [sys.executable, "-c", _HOST_SEQ],
                timeout=timeout_s, capture_output=True, text=True,
            )
            out = res.stdout or ""
            if res.returncode != 0:
                tail = (res.stderr or out).strip().splitlines()[-2:]
                err = "host_seq child rc={}: {}".format(
                    res.returncode, " | ".join(tail))
        except subprocess.TimeoutExpired as e:
            raw = e.stdout
            out = (raw.decode(errors="replace")
                   if isinstance(raw, bytes) else raw) or ""
            err = f"timed out after {timeout_s:.0f}s"
        extras = []
        for ln in out.splitlines():
            if not ln.startswith("HOST_SEQ_ROW "):
                continue
            try:
                r = json.loads(ln[len("HOST_SEQ_ROW "):])
                extras.append({
                    "metric": f"host_seq_{r['tag']}_nodes_per_sec",
                    "value": r["nodes_per_sec"],
                    "vs_ref_c_seq": round(
                        r["nodes_per_sec"] / REF_C_SEQ[r["tag"]], 3
                    ) if r["tag"] in REF_C_SEQ else None,
                    "parity": r["parity"],
                })
            except (ValueError, KeyError):
                continue  # torn line from a mid-write kill
        if err is not None:
            extras.append({"metric": "host_seq", "error": err})
        return extras
    except Exception as e:  # noqa: BLE001 — the bench line must survive
        return [{"metric": "host_seq",
                 "error": f"{type(e).__name__}: {e}"}]


def backend_alive(timeout_s: float = 240.0) -> tuple[bool, str | None]:
    """One tiny matmul in a subprocess: a dead TPU tunnel hangs backend
    init forever (observed: multi-hour axon outages), and a hang in the
    parent would eat the driver's whole budget without even printing the
    JSON line. Subprocess + timeout turns that into a clean error record.
    Returns (ok, error) with a crash's stderr tail preserved."""
    code = (
        "import jax, jax.numpy as jnp; "
        "x = jnp.ones((8, 8)); print(float((x @ x).sum()))"
    )
    try:
        res = subprocess.run(
            [sys.executable, "-c", code],
            timeout=timeout_s,
            capture_output=True,
            text=True,
        )
    except subprocess.TimeoutExpired:
        return False, (
            f"jax backend unreachable: device init hung for {timeout_s:.0f}s "
            "(tunnel down?)"
        )
    if res.returncode != 0:
        tail = (res.stderr or res.stdout).strip().splitlines()[-3:]
        return False, "jax backend init crashed: " + " | ".join(tail)
    return True, None


from contextlib import contextmanager, nullcontext


@contextmanager
def _env_override(key: str, value: str):
    """Temporarily set an env knob; on exit restore the previous value or
    pop the key (never clobber a user's explicit setting)."""
    prev = os.environ.get(key)
    os.environ[key] = value
    try:
        yield
    finally:
        if prev is None:
            os.environ.pop(key, None)
        else:
            os.environ[key] = prev


def _run_probe(code: str, ok_marker: str, timeout_s: float
               ) -> tuple[bool, str | None]:
    """One probe subprocess; returns (ok, error)."""
    try:
        res = subprocess.run(
            [sys.executable, "-c", code],
            timeout=timeout_s,
            capture_output=True,
            text=True,
        )
    except subprocess.TimeoutExpired:
        return False, f"probe timed out after {timeout_s:.0f}s (compile hang)"
    for line in res.stdout.splitlines():
        if line.startswith("PALLAS_PROBE_SKIP:"):
            backend = line.split(":", 1)[1]
            return False, f"backend is {backend!r}, not tpu"
    if res.returncode != 0 or ok_marker not in res.stdout:
        tail = (res.stderr or res.stdout).strip().splitlines()[-3:]
        return False, "probe failed: " + " | ".join(tail)
    return True, None


def probe_pallas(
    timeout_s: float = 300.0,
) -> tuple[bool, str | None, bool, str | None, bool, str | None]:
    """Compile + oracle-check the Pallas kernels, one FAMILY per subprocess.

    Subprocesses (not in-process try/except) because a Mosaic compile can
    *hang*, not just raise — the timeout converts that into a clean
    fallback instead of eating the driver's whole budget. The backend check
    also happens in the subprocess: initializing the TPU client in the
    parent first would lock a single-client runtime out from under the
    probe. Three independent verdicts with per-family blast radii:

      * lb1-family (lb1 + nqueens, hardware-proven, carries the headline)
        -> failure sets TTS_PALLAS=0 (everything falls back);
      * lb2 child kernel -> failure sets only TTS_PALLAS_LB2=0 (the lb1
        headline keeps its kernel path);
      * staged self kernel -> failure sets only TTS_LB2_STAGED=0.

    Returns (lb1_ok, lb1_err, lb2_ok, lb2_err, staged_ok, staged_err).
    """
    if os.environ.get("TTS_PALLAS", "1") == "0":
        return False, "disabled by TTS_PALLAS=0", False, None, False, None
    ok1, err1 = _run_probe(_PROBE, "PALLAS_PROBE_OK", timeout_s)
    if not ok1:
        return False, err1, False, None, False, None
    if os.environ.get("TTS_PALLAS_LB2", "1") == "0":
        # Operator already routed the lb2 family to jnp (e.g. dodging a
        # known Mosaic hang): don't re-hit the compile in the probe, and
        # don't let a passing probe claim a kernel path the measured run
        # won't take.
        return (True, None, False, "disabled by TTS_PALLAS_LB2=0",
                False, None)
    ok2, err2 = _run_probe(_PROBE_LB2, "PALLAS_LB2_OK", timeout_s)
    if not ok2:
        # The staged self kernel rides the lb2 family: don't spend another
        # probe window on it.
        return True, None, False, err2, False, None
    ok3, err3 = _run_probe(_PROBE_STAGED, "PALLAS_STAGED_OK", timeout_s)
    if not ok3:
        err3 = "staged probe: " + (err3 or "")
    return True, None, True, None, ok3, err3


def eval_microbench(problem, on_tpu: bool, iters: int | None = None) -> dict:
    """Pure-evaluator throughput on the search's exact chunk shape — the
    measured cross-check for the model-derived roofline (VERDICT r4 weak
    #5): if the search-loop MFU sits far below this, the gap is
    orchestration (pool ops, compaction, dispatch), not the kernel; if they
    match, the kernel is the ceiling. B matches HEADLINE_M so (a) the
    jnp-vs-Pallas headline-path pick is measured at the production chunk
    shape, not a 64x bigger one, and (b) the compiles warm exactly the
    evaluator the chosen path dispatches."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_tree_search.ops import pfsp_device as P

    t = problem.device_tables()
    n, m = problem.jobs, problem.machines
    B = HEADLINE_M if on_tpu else 4096
    if iters is None:
        # Keep the timed section comparable to the old B=65536 runs so
        # small chunks don't measure noise: scale repetitions inversely
        # with the batch, per backend (CPU's B is unchanged -> 20).
        base = 65536 if on_tpu else 4096
        iters = max(20, (base // B) * 20)
    rng = np.random.default_rng(5)
    prmu = rng.permuted(
        np.tile(np.arange(n, dtype=np.int32), (B, 1)), axis=1
    )
    limit1 = rng.integers(-1, n - 1, B).astype(np.int32)
    pd, ld = jnp.asarray(prmu), jnp.asarray(limit1)

    fn = jax.jit(lambda a, b: P.lb1_bounds(a, b, t))
    fn(pd, ld).block_until_ready()  # compile + warm
    t0 = time.time()
    for _ in range(iters):
        out = fn(pd, ld)
    out.block_until_ready()
    dt = time.time() - t0
    parents_per_sec = B * iters / dt
    # Same FLOP model + MFU formula as the search-loop roofline — the two
    # numbers must stay comparable (this microbench exists to cross-check
    # that roofline).
    rl = roofline(parents_per_sec, n, m, None, "lb1", problem=problem)
    return {
        "kernel": "lb1",
        "batch": B,
        "iters": iters,
        "bound_evals_per_sec": rl["bound_evals_per_sec"],
        "achieved_gflops": rl["achieved_gflops"],
        "mfu_pct": rl["mfu_pct"],
    }


COMPACT_MODES = ("scatter", "sort", "search", "dense")


def _phaseprof_armed() -> bool:
    """Session-level TTS_PHASEPROF=1: hardware sessions arm it for the
    decomposition stages; the default bench never pays the armed
    variant's compiles or callback clocks."""
    from tpu_tree_search.obs import phases as obs_phases

    return obs_phases.phase_profiling_enabled()


def phase_split_probe(problem, m: int, M: int, K: int = 64,
                      max_steps: int = 2) -> dict | None:
    """Measured per-phase cycle split from a short ARMED resident run
    (TTS_PHASEPROF=1, obs/phases.py): the real engine with its phase
    clocks on, bounded to ``max_steps`` dispatches.  The armed program is
    a separate cache-keyed variant, so the headline program is untouched.
    Returns ``{"ns", "shares", "cycles", "dominant"}`` or None (the probe
    is best-effort and must never cost the bench line)."""
    try:
        from tpu_tree_search.engine.resident import resident_search
        from tpu_tree_search.obs import phases as obs_phases

        with _env_override("TTS_PHASEPROF", "1"):
            res = resident_search(problem, m=m, M=M, K=K,
                                  max_steps=max_steps)
        pp = res.phase_profile
        if not pp or not pp.get("total"):
            return None
        dom = obs_phases.dominant_phase(pp)
        return {
            "ns": {k: int(v) for k, v in pp.items()},
            "shares": {k: round(v, 4)
                       for k, v in obs_phases.shares(pp).items()},
            "cycles": int(res.diagnostics.kernel_launches),
            "dominant": dom[0] if dom else None,
        }
    except Exception:  # noqa: BLE001 — calibration is best-effort
        return None


def eval_cycle_ms(problem, m: int, M: int, cycles: int = 64) -> float | None:
    """Measured evaluator-in-loop cost per cycle at the production chunk
    shape.

    When the phase profiler is armed for the session (``TTS_PHASEPROF=1``
    — hardware sessions arm it for the decomposition stages), the number
    comes from the profiler itself: the ``eval`` phase clock of a short
    armed resident run (``phase_split_probe``) — ONE decomposition
    mechanism, measured inside the real loop.  Otherwise (the CPU/default
    fallback) it is the original stripped while_loop whose body runs ONLY
    the evaluator — no pop, no compaction, no push
    (scripts/cycle_profile.py's c-loop, inlined so pick_compact can price
    the survivor path per mode).  A mode's maintenance share is then its
    measured cycle_ms minus this; the on-device ``push_rows`` counter
    carries the matching WORK series (docs/OBSERVABILITY.md).  Returns
    None on any failure — the decomposition is best-effort and must never
    cost the bench line."""
    from tpu_tree_search.obs import phases as obs_phases

    if obs_phases.phase_profiling_enabled():
        split = phase_split_probe(problem, m, M, K=cycles)
        if split and split["cycles"]:
            return round(split["ns"]["eval"] / 1e6 / split["cycles"], 3)
    try:
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax import lax

        from tpu_tree_search.engine.resident import (
            _make_program,
            resolve_capacity,
        )

        capacity, M = resolve_capacity(problem, M, None)
        prog = _make_program(problem, m, M, cycles, capacity,
                             jax.devices()[0])
        evaluate = prog._make_eval()
        n = problem.child_slots
        vals = jnp.asarray(np.tile(np.arange(n, dtype=np.int32), (M, 1)))
        aux = jnp.zeros((M,), jnp.int32)
        valid = jnp.ones((M,), bool)
        ub = jnp.int32(min(getattr(problem, "initial_ub", 2**30), 2**30))

        def body(carry):
            best, tree, cyc = carry
            keep, sol_inc, best = evaluate(vals, aux, valid, best)
            # Fold keep into the carry so nothing is dead-code-eliminated.
            tree = tree + jnp.sum(keep, dtype=jnp.int32) + sol_inc * 0
            return best, tree, cyc + 1

        fn = jax.jit(lambda: lax.while_loop(
            lambda c: c[2] < cycles, body, (ub, jnp.int32(0), jnp.int32(0))
        ))

        def block(out):
            for x in out:
                if hasattr(x, "block_until_ready"):
                    x.block_until_ready()
            return out

        block(fn())  # compile + warm
        t0 = time.time()
        block(fn())
        return round(1e3 * (time.time() - t0) / cycles, 3)
    except Exception:  # noqa: BLE001 — calibration is best-effort
        return None


@contextmanager
def _mode_timeout(seconds: float | None):
    """Best-effort hard wall-clock bound for one in-process measurement:
    ``SIGALRM`` + ``setitimer`` raise ``TimeoutError`` inside the running
    mode instead of merely gating the next one. Limitations (why the
    subprocess probes still exist): signals deliver only in the MAIN
    thread — elsewhere this is a no-op — and a native call that never
    returns to the interpreter (a truly hung Mosaic compile) postpones
    delivery until it does; long-but-finite compiles and runs ARE
    interrupted, which is the case the budget exists for."""
    import signal
    import threading

    if (
        seconds is None
        or threading.current_thread() is not threading.main_thread()
        or not hasattr(signal, "setitimer")
    ):
        yield
        return

    def _raise(signum, frame):
        raise TimeoutError(f"mode run exceeded its {seconds:.0f}s slice")

    old = signal.signal(signal.SIGALRM, _raise)
    signal.setitimer(signal.ITIMER_REAL, max(seconds, 1e-3))
    try:
        yield
    finally:
        signal.setitimer(signal.ITIMER_REAL, 0.0)
        signal.signal(signal.SIGALRM, old)


def pick_compact(run_fn, parity_fn, budget_s: float | None = None,
                 eval_ms: float | None = None, auto_mode: str | None = None,
                 phase_probe=None):
    """Measure ``run_fn()`` under each compaction mode (TTS_COMPACT) and
    pick the fastest PARITY-PASSING one (fallback: fastest overall — a
    fast-but-wrong mode must never displace a clean measurement, but if
    none is clean the caller's own parity gate reports it). Per-mode
    failures are recorded, never fatal.

    The stats blob records WHY a mode won, not just that it did: per mode,
    the measured device ms/cycle and — when the caller supplies the
    evaluator-only calibration ``eval_ms`` (``eval_cycle_ms``) — the
    implied maintenance (pop+compact+push) ms/cycle; ``auto_mode`` records
    what ``TTS_COMPACT=auto`` would have resolved for this config, so the
    artifact shows whether the policy table agrees with the measurement.

    ``phase_probe`` (armed sessions: a zero-arg callable wrapping
    ``phase_split_probe``) runs once per surviving mode UNDER that mode's
    ``TTS_COMPACT`` pin, so the row records the measured per-phase cycle
    split of each compaction mode — the phase-profiler counterpart of the
    ``eval_ms`` subtraction (one decomposition mechanism when armed).

    ``budget_s`` is a HARD bound on the whole A/B, not just a start gate:
    each mode runs inside its remaining slice of the budget under
    ``_mode_timeout`` (SIGALRM), so a mode that begins just under the
    budget is interrupted rather than overrunning arbitrarily (ADVICE r5).
    The first mode gets the full budget (the old single-mode behavior is
    the floor — if IT times out, the caller's fallback plain run still
    produces the record); later modes get what is left and are skipped
    outright once nothing is. Residual overshoot is limited to native
    calls that never re-enter the interpreter (see ``_mode_timeout``).
    Returns ``(stats, best_run)``; ``(None, None)`` if every mode failed
    to run. Shared by the headline A/B and the N-Queens probe so the mode
    list and selection rule cannot drift apart."""
    runs, nps, par, errors = {}, {}, {}, {}
    phase_splits: dict = {}
    t0 = time.monotonic()
    skipped = []
    for i, mode in enumerate(COMPACT_MODES):
        remaining = (
            None if budget_s is None
            else budget_s - (time.monotonic() - t0)
        )
        # Only the FIRST mode is exempt from the skip (it still runs under
        # the full-budget timeout): a mode that burns the budget and then
        # fails must still stop the A/B (the guarantee is a bound on total
        # wall time, success or not).
        if i > 0 and remaining is not None and remaining <= 0:
            skipped.append(mode)
            continue
        try:
            with _env_override("TTS_COMPACT", mode), \
                    _mode_timeout(budget_s if i == 0 else remaining):
                r = run_fn()
                if phase_probe is not None:
                    # Short armed run under the same mode pin: the row's
                    # measured phase split (still inside the timeout).
                    phase_splits[mode] = phase_probe()
        except TimeoutError as e:
            errors[mode] = f"TimeoutError: {e}"
            continue
        except Exception as e:  # noqa: BLE001 — one mode must not kill the rest
            errors[mode] = f"{type(e).__name__}: {e}"
            continue
        runs[mode] = r
        nps[mode] = round(r[1], 1)
        par[mode] = bool(parity_fn(r))
    decomp = {}
    for mode, r in runs.items():
        # r = (result, nps, elapsed, device_phase): per-mode cycle cost
        # from the run's own diagnostics (guarded — unit tests pass stubs).
        cyc = getattr(getattr(r[0], "diagnostics", None),
                      "kernel_launches", 0)
        if cyc and r[3]:
            row = {"cycle_ms": round(1e3 * r[3] / cyc, 3)}
            if eval_ms is not None:
                row["eval_ms"] = eval_ms
                row["maint_ms"] = round(row["cycle_ms"] - eval_ms, 3)
            if phase_splits.get(mode):
                row["phases"] = phase_splits[mode]
            decomp[mode] = row
    if not runs:
        # Preserve the per-mode diagnostics even when every mode failed —
        # the caller falls back to a plain run, but the record must show
        # that three measured modes crashed and why.
        return ({"picked": None, "errors": errors} if errors else None), None
    clean = {k: v for k, v in runs.items() if par[k]}
    pool = clean or runs
    pick = max(pool, key=lambda k: pool[k][1])
    stats = {
        "picked": pick,
        "nodes_per_sec": nps,
        "parity": par,
        **({"decomp": decomp} if decomp else {}),
        **({"auto": auto_mode} if auto_mode is not None else {}),
        **({"errors": errors} if errors else {}),
        **({"skipped_budget": skipped} if skipped else {}),
    }
    return stats, runs[pick]


def _compact_ctx(stats):
    """Context manager pinning TTS_COMPACT to a pick_compact result's
    winner; a no-op when there is no usable pick."""
    if stats and stats.get("picked"):
        return _env_override("TTS_COMPACT", stats["picked"])
    return nullcontext()


def _drive_dispatch_loop(problem, m: int, M: int, K: int, depth: int,
                         half_lat_s: float) -> tuple[int, float]:
    """Drive the resident program's dispatch loop by hand at a given
    pipeline depth with an injected host round-trip latency: sleep
    ``half_lat_s`` before each enqueue (command travel) and after each
    scalar read (response travel) — the tunnel model. Returns
    ``(dispatches, wall_seconds)`` of the device phase. Deterministic for
    a fixed (problem, m, M, K): both depths run the identical dispatch
    sequence, so the wall delta is pure overlap."""
    from collections import deque

    import jax

    from tpu_tree_search.engine.device import warmup
    from tpu_tree_search.engine.resident import (
        _make_program,
        resolve_capacity,
    )
    from tpu_tree_search.pool import SoAPool
    from tpu_tree_search.problems.base import INF_BOUND, index_batch

    capacity, M = resolve_capacity(problem, M, None)
    prog = _make_program(problem, m, M, K, capacity, jax.devices()[0])
    pool = SoAPool(problem.node_fields())
    pool.push_back(index_batch(problem.root(), 0))
    best = getattr(problem, "initial_ub", INF_BOUND)
    _, _, best = warmup(problem, pool, best, m)
    state = prog.init_state(pool.as_batch(), best)
    q: deque = deque()
    dispatches = 0
    done = None
    t0 = time.perf_counter()
    while True:
        while len(q) < depth:
            if half_lat_s:
                time.sleep(half_lat_s)  # command latency (host -> device)
            out = prog.step(state)
            state = prog.carry(out)
            q.append(out)
        # Keep each consumed output bound one iteration longer (`done`):
        # on the CPU backend, dropping an output tuple whose pool buffers
        # were donated into a still-in-flight dispatch blocks in the
        # destructor until that dispatch finishes — which would silently
        # serialize the pipeline this harness exists to measure.
        done = q.popleft()
        size = prog.read_scalars(done)[3]
        if half_lat_s:
            time.sleep(half_lat_s)  # response latency (device -> host)
        dispatches += 1
        if size < m:
            while q:  # speculative no-ops
                done = q.popleft()
                prog.read_scalars(done)
            break
    return dispatches, time.perf_counter() - t0


def simulated_latency_ab(problem=None, m: int = 25, M: int = 512,
                         K: int = 8, half_lat_s: float | None = None) -> dict:
    """Pipeline A/B on the simulated-latency CPU harness: the same full
    search driven at depth 1 (synchronous — every dispatch pays the
    injected round trip with the device idle) vs depth 2 (speculative —
    the round trip overlaps device compute). The expected per-dispatch
    drop is ``min(T_dev, round_trip)``; the default latency is calibrated
    to ~60%% of the measured per-dispatch device time so the full
    round-trip drop is achievable, which is exactly the regime of the real
    tunnel (~360 ms round trips vs multi-K-cycle dispatch blocks)."""
    if problem is None:
        from tpu_tree_search.problems import NQueensProblem

        problem = NQueensProblem(N=11)
    # Calibrate: latency-free depth-1 passes measure T_dev per dispatch —
    # the first warms the compile, the second is the measurement.
    _drive_dispatch_loop(problem, m, M, K, depth=1, half_lat_s=0.0)
    n0, t_cal = _drive_dispatch_loop(problem, m, M, K, depth=1,
                                     half_lat_s=0.0)
    t_dev = t_cal / max(n0, 1)
    if half_lat_s is None:
        # round_trip = 0.6 * T_dev keeps T_dev > round_trip, the regime
        # where depth 2 hides the FULL round trip (drop = min(T_dev, L)).
        half_lat_s = max(0.002, 0.3 * t_dev)
    n1, t1 = _drive_dispatch_loop(problem, m, M, K, 1, half_lat_s)
    n2, t2 = _drive_dispatch_loop(problem, m, M, K, 2, half_lat_s)
    per1 = t1 / max(n1, 1)
    per2 = t2 / max(n2, 1)
    return {
        "dispatches": n1,
        "t_dev_ms": round(1e3 * t_dev, 3),
        "round_trip_ms": round(1e3 * 2 * half_lat_s, 3),
        "depth1_ms_per_dispatch": round(1e3 * per1, 3),
        "depth2_ms_per_dispatch": round(1e3 * per2, 3),
        "drop_ms_per_dispatch": round(1e3 * (per1 - per2), 3),
    }


def _dispatch_latency_rows(extras: list, on_tpu: bool) -> None:
    """Dispatch-latency microbench rows (never fail the bench):

    * ``dispatch_pipeline_sim_ab`` — the simulated-latency CPU harness
      above, on every backend (the no-TPU-window proof that depth 2 hides
      the scalar-read round trip).
    * on TPU: per-dispatch host wall at K=1 vs K=max, depth 1 vs 2, on the
      headline config — bounded by max_steps so each cell costs a few
      dispatches; these are the numbers that show the ~360 ms tunnel round
      trip amortized (K) and overlapped (depth).
    """
    try:
        extras.append({
            "metric": "dispatch_pipeline_sim_ab",
            **simulated_latency_ab(),
        })
    except Exception as e:  # noqa: BLE001
        extras.append({
            "metric": "dispatch_pipeline_sim_ab",
            "error": f"{type(e).__name__}: {e}",
        })
    if not on_tpu:
        return
    from tpu_tree_search.engine.resident import resident_search
    from tpu_tree_search.problems import PFSPProblem

    for K, steps in ((1, 32), (4096, 4)):
        for depth in (1, 2):
            metric = f"dispatch_wall_K{K}_depth{depth}_ms"
            try:
                with _env_override("TTS_PIPELINE", str(depth)):
                    prob = PFSPProblem(inst=14, lb="lb1", ub=1)
                    resident_search(prob, m=25, M=HEADLINE_M, K=K,
                                    max_steps=1)  # warm
                    res = resident_search(prob, m=25, M=HEADLINE_M, K=K,
                                          max_steps=steps)
                dev_s = (res.phases[1].seconds if len(res.phases) > 1
                         else res.elapsed)
                # Pipelining drains up to depth-1 extra dispatches at cut.
                n_disp = steps + depth - 1
                extras.append({
                    "metric": metric,
                    "value": round(1e3 * dev_s / max(n_disp, 1), 3),
                    "unit": "ms/dispatch",
                    "dispatches": n_disp,
                    "cycles": res.diagnostics.kernel_launches,
                })
            except Exception as e:  # noqa: BLE001
                extras.append({
                    "metric": metric, "error": f"{type(e).__name__}: {e}",
                })
    # Headline-config pipeline on/off A/B (bounded): same K, same steps,
    # only TTS_PIPELINE flips — the wall delta is the hidden round trip.
    try:
        from tpu_tree_search.engine.resident import resident_search

        prob = PFSPProblem(inst=14, lb="lb1", ub=1)
        walls = {}
        for depth in (1, 2):
            with _env_override("TTS_PIPELINE", str(depth)):
                resident_search(prob, m=25, M=HEADLINE_M, max_steps=1)
                res = resident_search(prob, m=25, M=HEADLINE_M, max_steps=8)
            walls[depth] = (res.phases[1].seconds if len(res.phases) > 1
                            else res.elapsed)
        extras.append({
            "metric": "pipeline_ab_headline",
            "depth1_s": round(walls[1], 3),
            "depth2_s": round(walls[2], 3),
            "speedup": round(walls[1] / max(walls[2], 1e-9), 3),
        })
    except Exception as e:  # noqa: BLE001
        extras.append({
            "metric": "pipeline_ab_headline",
            "error": f"{type(e).__name__}: {e}",
        })


def _batch_ab_rows(extras: list) -> None:
    """Instance-batching A/B on the CPU-sim harness (never fails the
    bench): the same N same-shape-class jobs run serially through
    ``resident_search`` vs through ``engine/batched.batched_search`` at
    B in {1, 4, 8}. Reported per width: batch wall, aggregate nodes/s,
    mean per-job latency, speedup over serial, and bit-identity of every
    job against its solo run (the batching contract — a throughput win
    that perturbed a single count would be a bug, not a result). B=1 is
    the degenerate case and should run at ~serial speed. Wider batches
    amortize per-dispatch host overhead across tenants — a device-side
    effect: on the CPU sim the unrolled slots multiply per-cycle compute
    (the off-chip bottleneck), so expect b4/b8 to LOSE here; the row's
    job is structure + parity evidence, and the hardware session banks
    the real speedup (scripts/hw_session.sh)."""
    from tpu_tree_search.engine.batched import batched_search
    from tpu_tree_search.engine.resident import resident_search
    from tpu_tree_search.problems import NQueensProblem

    n_jobs, m, M, K = 8, 5, 64, 8

    def _mk():
        return NQueensProblem(N=9)

    try:
        problem = _mk()
        resident_search(problem, m=m, M=M, K=K)  # warm the solo program
        t0 = time.perf_counter()
        serial = [resident_search(problem, m=m, M=M, K=K)
                  for _ in range(n_jobs)]
        serial_s = time.perf_counter() - t0
        golden = [(r.explored_tree, r.explored_sol, r.best) for r in serial]
        nodes = sum(r.explored_tree for r in serial)
        row = {
            "metric": "batch_ab_sim",
            "jobs": n_jobs,
            "serial_s": round(serial_s, 3),
            "serial_nodes_per_sec": round(nodes / max(serial_s, 1e-9), 1),
            "serial_job_latency_ms": round(1e3 * serial_s / n_jobs, 2),
        }
        for B in (1, 4, 8):
            batched_search(problem, n_jobs=B, B=B, m=m, M=M, K=K)  # warm
            t0 = time.perf_counter()
            results = batched_search(problem, n_jobs=n_jobs, B=B,
                                     m=m, M=M, K=K)
            wall = time.perf_counter() - t0
            parity = (
                [(r.explored_tree, r.explored_sol, r.best) for r in results]
                == golden
            )
            row[f"b{B}_s"] = round(wall, 3)
            row[f"b{B}_nodes_per_sec"] = round(nodes / max(wall, 1e-9), 1)
            row[f"b{B}_job_latency_ms"] = round(1e3 * wall / n_jobs, 2)
            row[f"b{B}_speedup"] = round(serial_s / max(wall, 1e-9), 3)
            row[f"b{B}_parity"] = parity
        row["parity"] = all(row[f"b{B}_parity"] for B in (1, 4, 8))
        extras.append(row)
    except Exception as e:  # noqa: BLE001 — A/B rows never fail a bench
        extras.append({
            "metric": "batch_ab_sim",
            "error": f"{type(e).__name__}: {e}",
        })


def steal_ab(problem=None, m: int = 5, M: int = 64, D: int = 1,
             hosts: int = 6, pods: int = 2,
             ici_lat_s: float = 0.002, dcn_lat_s: float = 0.25,
             interval_s: float = 0.005) -> dict:
    """Hierarchical-stealing A/B on the CPU-sim virtual-host harness
    (ISSUE 14 acceptance row): the same dist-tier search, flat vs hier
    (``TTS_STEAL``), over 6 virtual hosts in 2 pods (``TTS_PODS``) with
    injected asymmetric link latencies (cheap ICI, expensive DCN) and
    adversarial initial imbalance — one rich host per pod (hosts 0 and
    ``hosts//2``), every other host starts empty. Flat's matching is
    topology-blind: its size-ordered donor->needy zip systematically
    pairs rich hosts with needy hosts ACROSS pods, paying the injected
    DCN latency while a same-pod donor sits unused, and its tail ships
    end-of-run scraps over the same expensive link. Hier feeds every
    starved host from its own pod over ICI and takes the far link only
    for bulk quanta that amortize the latency (parallel/topology.py).
    Reported per mode: wall time, mean worker idle fraction (from the
    drained host trace, obs/report.summarize), donation totals, and the
    resolved policy — parity-gated on bit-identical node counts vs
    sequential (N-Queens never prunes, so ANY steal schedule must
    reproduce them)."""
    from tpu_tree_search.engine import sequential_search
    from tpu_tree_search.obs import events as obs_events
    from tpu_tree_search.obs import report as obs_report
    from tpu_tree_search.parallel.dist import dist_search
    from tpu_tree_search.problems import NQueensProblem

    if problem is None:
        problem = NQueensProblem(N=10)
    seq = sequential_search(problem)
    golden = (seq.explored_tree, seq.explored_sol)
    rich = (0, hosts // 2)  # one donor per pod

    def skew(warm, host_id, num_hosts):
        n = len(next(iter(warm.values())))
        if host_id == rich[0]:
            return {k: v[: n // 2] for k, v in warm.items()}
        if host_id == rich[1]:
            return {k: v[n // 2:] for k, v in warm.items()}
        return {k: v[:0] for k, v in warm.items()}

    # Warm the compile cache outside the measured pair: the first dist run
    # traces the chunk program, and that cost must not land in one arm's
    # busy spans (no latency injection, default balanced partition).
    dist_search(problem, m=m, M=M, D=D, num_hosts=hosts,
                steal_interval_s=interval_s)

    out: dict = {
        "metric": "steal_ab_sim",
        "hosts": hosts,
        "pods": pods,
        "workers_per_host": D,
        "ici_lat_ms": round(1e3 * ici_lat_s, 1),
        "dcn_lat_ms": round(1e3 * dcn_lat_s, 1),
        "golden_tree": golden[0],
    }
    for mode in ("flat", "hier"):
        with _env_override("TTS_STEAL", mode), \
                _env_override("TTS_PODS", str(pods)), \
                _env_override("TTS_SIM_LAT_ICI", str(ici_lat_s)), \
                _env_override("TTS_SIM_LAT_DCN", str(dcn_lat_s)), \
                _env_override("TTS_OBS", "host"):
            obs_events.reset()
            t0 = time.perf_counter()
            res = dist_search(problem, m=m, M=M, D=D, num_hosts=hosts,
                              steal_interval_s=interval_s,
                              partition_fn=skew)
            wall = time.perf_counter() - t0
            summ = obs_report.summarize(obs_events.drain())
        idle = [w["idle_fraction"] for w in summ["idle"].values()]
        links = {
            k: {"attempts": v["attempts"], "hits": v["hits"]}
            for k, v in summ["steal_links"].items()
        }
        out[f"{mode}_s"] = round(wall, 3)
        out[f"{mode}_idle_frac"] = round(
            sum(idle) / len(idle), 4) if idle else None
        out[f"{mode}_blocks"] = (res.comm or {}).get("blocks_received")
        out[f"{mode}_nodes"] = (res.comm or {}).get("nodes_received")
        out[f"{mode}_links"] = links
        out[f"{mode}_parity"] = (
            (res.explored_tree, res.explored_sol) == golden
        )
        if mode == "hier":
            out["policy"] = res.steal_policy
    out["parity"] = out["flat_parity"] and out["hier_parity"]
    out["speedup"] = round(out["flat_s"] / max(out["hier_s"], 1e-9), 3)
    if (out["flat_idle_frac"] is not None
            and out["hier_idle_frac"] is not None):
        out["idle_drop"] = round(
            out["flat_idle_frac"] - out["hier_idle_frac"], 4)
    return out


def _steal_ab_rows(extras: list) -> None:
    """Hierarchical-stealing A/B row (never fails the bench)."""
    try:
        extras.append(steal_ab())
    except Exception as e:  # noqa: BLE001 — A/B rows never fail a bench
        extras.append({
            "metric": "steal_ab_sim",
            "error": f"{type(e).__name__}: {e}",
        })


def _bytes_ab_rows(extras: list) -> None:
    """Narrow-node-storage A/B (problems/base.py TTS_NARROW — never fails
    the bench): per arm (auto vs 0) on real ta014 shapes, the host bytes
    per node and per prmu row from ``node_fields`` (the 80B -> 20B
    headline), plus measured artifacts from a budgeted resident run —
    checkpoint file size and the snapshot's host-transfer payload bytes —
    and a complete CPU-sim search on a reduced instance whose counts gate
    the row (``parity``): the encoding at rest must never change what the
    search explores. On the CPU sim the wall delta is noise; the byte
    columns are the evidence, the hardware session banks the bandwidth
    effect (scripts/hw_session.sh NARROW_AB)."""
    import tempfile

    import numpy as np

    from tpu_tree_search.engine import checkpoint as _ckpt
    from tpu_tree_search.engine.resident import resident_search
    from tpu_tree_search.problems import PFSPProblem
    from tpu_tree_search.problems.pfsp import taillard

    try:
        row = {"metric": "bytes_ab", "inst": "ta014"}
        ptm = taillard.reduced_instance(14, jobs=10, machines=5)
        counts = {}
        for arm, mode in (("narrow", "auto"), ("wide", "0")):
            with _env_override("TTS_NARROW", mode):
                prob = PFSPProblem(inst=14)
                fields = prob.node_fields()
                per_node = sum(
                    int(np.prod(shape, dtype=np.int64))
                    * np.dtype(dt).itemsize
                    for shape, dt in fields.values()
                )
                row[f"{arm}_bytes_per_node"] = per_node
                row[f"{arm}_prmu_bytes"] = (
                    int(np.prod(fields["prmu"][0], dtype=np.int64))
                    * fields["prmu"][1].itemsize
                )
                with tempfile.TemporaryDirectory() as td:
                    path = os.path.join(td, "ab.ckpt")
                    resident_search(prob, m=8, M=256, K=2, max_steps=2,
                                    checkpoint_path=path)
                    row[f"{arm}_ckpt_bytes"] = os.path.getsize(path)
                    snap = _ckpt.load(path, prob)
                    row[f"{arm}_snapshot_host_bytes"] = sum(
                        np.asarray(v).nbytes for v in snap.batch.values()
                    )
                small = PFSPProblem(lb="lb1", ub=0, p_times=ptm)
                resident_search(small, m=8, M=64, K=8)  # warm
                t0 = time.perf_counter()
                res = resident_search(small, m=8, M=64, K=8)
                row[f"{arm}_sim_wall_s"] = round(time.perf_counter() - t0, 3)
                counts[arm] = (res.explored_tree, res.explored_sol, res.best)
        row["prmu_shrink"] = round(
            row["wide_prmu_bytes"] / max(row["narrow_prmu_bytes"], 1), 2)
        row["node_shrink"] = round(
            row["wide_bytes_per_node"] / max(row["narrow_bytes_per_node"], 1),
            2)
        row["ckpt_shrink"] = round(
            row["wide_ckpt_bytes"] / max(row["narrow_ckpt_bytes"], 1), 2)
        row["parity"] = counts["narrow"] == counts["wide"]
        extras.append(row)
    except Exception as e:  # noqa: BLE001 — A/B rows never fail a bench
        extras.append({
            "metric": "bytes_ab",
            "error": f"{type(e).__name__}: {e}",
        })


def _megakernel_ab_rows(extras: list, on_tpu: bool) -> None:
    """One-kernel-cycle A/B (ops/megakernel.py — the keep/retire evidence
    row, docs/HW_VALIDATION.md). Off-chip the row is a PARITY GATE only:
    ``TTS_MEGAKERNEL=force`` arms the fused Pallas cycle in interpret mode
    (same program structure, reference semantics) and every count must be
    bit-identical to the off build — no timing claim, interpret wall time
    means nothing. A third arm forces the STREAMED grid form
    (``TTS_MEGAKERNEL_MT``) under the same gate, and an M-ladder records
    the auto resolver's decision per pool-size rung (the past-2^16 rung
    must arm tiled). On TPU the row adds the timed A/B/tiled triple on
    ta014 lb1 at M=1024 — off vs force vs tiled nodes/s, speedups, golden
    parity for all arms, and a phase-profiled roofline audit per arm
    (``*_roofline_mem``, obs/roofline.py) — the numbers the round-6
    keep/retire bars judge."""
    from tpu_tree_search.engine.resident import resident_search
    from tpu_tree_search.problems import NQueensProblem, PFSPProblem

    row = {"metric": "megakernel_ab"}
    try:
        import numpy as np

        rng = np.random.default_rng(13)
        ptm = np.ascontiguousarray(
            rng.integers(1, 100, size=(5, 8)).astype(np.int32))
        cases = [
            ("nqueens", lambda: NQueensProblem(N=10)),
            ("lb1", lambda: PFSPProblem(lb="lb1", ub=0, p_times=ptm)),
            ("lb2", lambda: PFSPProblem(lb="lb2", ub=0, p_times=ptm)),
        ]
        parity = True
        for name, mk in cases:
            with _env_override("TTS_MEGAKERNEL", "0"):
                off = resident_search(mk(), m=5, M=64, K=8)
            with _env_override("TTS_MEGAKERNEL", "force"):
                on = resident_search(mk(), m=5, M=64, K=8)
            # Streamed-grid arm: a forced Mt=16 at M=64 tiles the pool
            # axis 4-wide — the double-buffered HBM->VMEM form must stay
            # bit-identical to both the off build and the single-tile arm.
            with _env_override("TTS_MEGAKERNEL", "force"), \
                    _env_override("TTS_MEGAKERNEL_MT", "16"):
                tiled = resident_search(mk(), m=5, M=64, K=8)
            ok = (
                on.megakernel == "on"
                and (on.explored_tree, on.explored_sol, on.best)
                == (off.explored_tree, off.explored_sol, off.best)
            )
            tok = (
                tiled.megakernel == "on" and tiled.megakernel_tiled
                and tiled.megakernel_mt == 16
                and (tiled.explored_tree, tiled.explored_sol, tiled.best)
                == (off.explored_tree, off.explored_sol, off.best)
            )
            row[f"{name}_parity"] = ok
            row[f"{name}_tiled_parity"] = tok
            if not ok:
                row[f"{name}_reason"] = on.megakernel_reason
            if not tok:
                row[f"{name}_tiled_reason"] = tiled.megakernel_reason
            parity = parity and ok and tok
        row["parity"] = parity

        # -- pool-size ladder (the streamed/tiled axis evidence) ----------
        # Decision rows at every rung: what the AUTO resolver does at this
        # M (patching the backend gate on so the rows mean the same thing
        # on- and off-chip) — the past-2^16 rung must arm TILED with a
        # recorded Mt, the refusal the streaming rewrite removed. The
        # smallest rung also EXECUTES the off/tiled pair off-chip as a
        # parity fact (interpret wall time means nothing; the timed
        # evidence stays on the TPU rows below).
        from tpu_tree_search.ops import megakernel as MK

        ladder = []
        orig_on_tpu = MK._native_kind
        MK._native_kind = ((lambda device=None: "tpu") if not on_tpu
                           else orig_on_tpu)
        try:
            for Mr in (4096, 16384, 65536):
                entry = {"M": Mr}
                dec = MK.resolve(NQueensProblem(N=10), Mr)
                entry["auto_enabled"] = dec.enabled
                entry["auto_mt"] = dec.mt
                entry["auto_grid"] = dec.grid
                if dec.reason:
                    entry["auto_reason"] = dec.reason
                ladder.append(entry)
        finally:
            MK._native_kind = orig_on_tpu
        if parity:
            Mr = 4096
            with _env_override("TTS_MEGAKERNEL", "0"):
                off = resident_search(NQueensProblem(N=10), m=5, M=Mr, K=2)
            with _env_override("TTS_MEGAKERNEL", "force"), \
                    _env_override("TTS_MEGAKERNEL_MT", str(Mr // 4)):
                tiled = resident_search(
                    NQueensProblem(N=10), m=5, M=Mr, K=2)
            ladder[0]["exec_tiled_parity"] = (
                tiled.megakernel == "on" and tiled.megakernel_tiled
                and (tiled.explored_tree, tiled.explored_sol)
                == (off.explored_tree, off.explored_sol)
            )
        row["m_ladder"] = ladder
        if on_tpu and parity:
            import contextlib

            timed = {}
            # Third arm: forced Mt=256 at M=1024 streams the pool 4-wide —
            # the grid form's timed number next to the single-tile one.
            for label, env, mt in (("off", "0", None),
                                   ("force", "force", None),
                                   ("tiled", "force", "256")):
                with contextlib.ExitStack() as stack:
                    stack.enter_context(
                        _env_override("TTS_MEGAKERNEL", env))
                    if mt is not None:
                        stack.enter_context(
                            _env_override("TTS_MEGAKERNEL_MT", mt))
                    resident_search(PFSPProblem(inst=14, lb="lb1", ub=1),
                                    m=25, M=1024)  # warm/compile
                    t0 = time.perf_counter()
                    res = resident_search(
                        PFSPProblem(inst=14, lb="lb1", ub=1), m=25, M=1024)
                    wall = time.perf_counter() - t0
                    # Separate phase-profiled pass: the roofline audit
                    # needs the phase clocks, whose instrumented build
                    # must never time the A/B arms themselves.
                    stack.enter_context(
                        _env_override("TTS_PHASEPROF", "1"))
                    prof = resident_search(
                        PFSPProblem(inst=14, lb="lb1", ub=1), m=25, M=1024)
                timed[label] = (res, wall)
                row[f"{label}_s"] = round(wall, 3)
                row[f"{label}_nodes_per_sec"] = round(
                    res.explored_tree / max(wall, 1e-9), 1)
                row[f"{label}_megakernel"] = res.megakernel
                if res.megakernel_mt:
                    row[f"{label}_mt"] = res.megakernel_mt
                if res.megakernel_reason:
                    row[f"{label}_reason"] = res.megakernel_reason
                if prof.roofline is not None:
                    row[f"{label}_roofline_mem"] = prof.roofline
            row["speedup"] = round(
                timed["off"][1] / max(timed["force"][1], 1e-9), 3)
            row["speedup_tiled"] = round(
                timed["off"][1] / max(timed["tiled"][1], 1e-9), 3)
            row["tpu_parity"] = (
                (timed["off"][0].explored_tree, timed["off"][0].explored_sol,
                 timed["off"][0].best)
                == (timed["force"][0].explored_tree,
                    timed["force"][0].explored_sol, timed["force"][0].best)
                == (timed["tiled"][0].explored_tree,
                    timed["tiled"][0].explored_sol, timed["tiled"][0].best)
            )
        extras.append(row)
    except Exception as e:  # noqa: BLE001 — A/B rows never fail a bench
        row["error"] = f"{type(e).__name__}: {e}"
        extras.append(row)


def run_config(problem, m: int, M: int):
    """Warm-up run (compiles) + measured run; returns
    (result, nodes/s, elapsed, device_phase_s)."""
    from tpu_tree_search.engine.resident import resident_search

    resident_search(problem, m=m, M=M)
    t0 = time.time()
    res = resident_search(problem, m=m, M=M)
    elapsed = time.time() - t0
    device_phase = res.phases[1].seconds if len(res.phases) > 1 else res.elapsed
    return res, res.explored_tree / max(device_phase, 1e-9), elapsed, device_phase


def main() -> int:
    partial = BenchPartial()
    partial.install_sigterm()
    try:
        return _main(partial)
    except BaseException as e:  # noqa: BLE001 — provenance, then re-raise
        partial.finish(1, f"crashed: {type(e).__name__}: {e}")
        raise


# -- fleet saturation (`python bench.py fleet_sat`) --------------------------

FLEET_SAT_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "FLEET_SAT.json")


def fleet_sat_main() -> int:
    """``python bench.py fleet_sat``: drive an in-process fleet (router +
    N daemons) through the seeded loadgen rate ladder and bank the
    saturation curve — offered jobs/s vs achieved jobs/s and p50/p99
    queue-wait ms, overall and per shape class — as FLEET_SAT.json.

    The curve is banked flush-as-you-go (one atomic rewrite per rate
    point), so a wall-clock kill still leaves a usable prefix — the same
    lesson BENCH_PARTIAL.json encodes. Knobs: TTS_FLEET_SAT_RATES
    (comma list of offered jobs/s), TTS_FLEET_SAT_JOBS (jobs per rate),
    TTS_FLEET_SAT_DAEMONS, TTS_FLEET_SAT_SEED, TTS_FLEET_SAT_OUT.
    CPU-sim runs (JAX_PLATFORMS=cpu — the CI smoke) write to tempdir to
    keep the working tree clean; hardware sessions keep the committed
    path (scripts/hw_session.sh stage 9b)."""
    partial = BenchPartial()
    partial.install_sigterm()
    import tempfile as _tempfile

    from tpu_tree_search.cli import enable_compile_cache
    from tpu_tree_search.fleet.loadgen import make_plan, saturation_curve
    from tpu_tree_search.fleet.router import FleetRouter
    from tpu_tree_search.serve.server import ServeDaemon

    enable_compile_cache()
    cpu = os.environ.get("JAX_PLATFORMS", "").startswith("cpu")
    out = os.environ.get("TTS_FLEET_SAT_OUT") or (
        os.path.join(_tempfile.gettempdir(), "FLEET_SAT.json") if cpu
        else FLEET_SAT_PATH)
    rates = [float(x) for x in os.environ.get(
        "TTS_FLEET_SAT_RATES", "0.5,1,2").split(",") if x.strip()]
    jobs_per_rate = int(os.environ.get("TTS_FLEET_SAT_JOBS", "6"))
    n_daemons = int(os.environ.get("TTS_FLEET_SAT_DAEMONS", "2"))
    seed = int(os.environ.get("TTS_FLEET_SAT_SEED", "0"))
    doc = {
        "metric": "fleet_saturation_curve",
        "daemons": n_daemons,
        "jobs_per_rate": jobs_per_rate,
        "seed": seed,
        "commit": _git_head(),
        "contracts": contracts_fingerprint(),
        "platform": "cpu-sim" if cpu else "accelerator",
        "status": "running",
        "points": [],
    }

    def bank() -> None:
        doc["updated"] = time.strftime("%Y-%m-%d %H:%M:%S UTC",
                                       time.gmtime())
        tmp = out + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, out)

    bank()
    state_root = _tempfile.mkdtemp(prefix="fleet_sat_")
    partial.stage("fleet_up", "running", daemons=n_daemons)
    daemons = [ServeDaemon(port=0,
                           state_dir=os.path.join(state_root, f"d{i}"))
               for i in range(n_daemons)]
    for d in daemons:
        d.start()
    router = FleetRouter(port=0,
                         state_dir=os.path.join(state_root, "fleet"),
                         daemons=[d.url for d in daemons],
                         scrape_interval_s=0.3, pull_interval_s=1.0)
    router.start()
    partial.stage("fleet_up", "ok", router=router.url,
                  daemons=[d.url for d in daemons])
    try:
        # Pre-warm every class in the mix once (make_plan's own class
        # set), so the curve measures queueing, not first-compile — the
        # same reason the main bench warms before timing.
        partial.stage("fleet_warm", "running")
        warm_specs = {}
        for row in make_plan(seed, 24, 100.0):
            spec = {k: v for k, v in row["spec"].items()
                    if k not in ("max_steps", "label")}
            warm_specs.setdefault(json.dumps(spec, sort_keys=True), spec)
        import urllib.request as _rq

        for spec in warm_specs.values():
            spec = dict(spec)
            spec["max_steps"] = 8
            req = _rq.Request(router.url + "/submit",
                              data=json.dumps(spec).encode(),
                              headers={"Content-Type": "application/json"})
            with _rq.urlopen(req, timeout=600) as r:
                json.loads(r.read().decode())
        deadline = time.time() + 600
        while time.time() < deadline:
            if all(j.brief()["state"] in ("done", "failed", "cancelled")
                   for j in router.jobs.all()):
                break
            time.sleep(0.5)
        partial.stage("fleet_warm", "ok", classes=len(warm_specs))

        def on_point(row: dict) -> None:
            doc["points"].append(row)
            bank()
            partial.stage(f"rate_{row['offered_jobs_per_s']:g}", "ok",
                          achieved=row["achieved_jobs_per_s"],
                          p99_ms=row["queue_wait_ms_p99"],
                          done=row["done"])

        saturation_curve(router.url, rates, seed=seed,
                         jobs_per_rate=jobs_per_rate,
                         steps_scale=12, steps_cap=80,
                         timeout_s=600.0, on_point=on_point)
        doc["status"] = "complete"
        bank()
        print(json.dumps({"metric": "fleet_saturation_curve",
                          "points": len(doc["points"]),
                          "artifact": out}))
        partial.finish(0)
        return 0
    finally:
        router.close()
        for d in daemons:
            d.scheduler.drain(timeout_s=30.0)
            d.close()


# -- GPU headline session (`python bench.py gpu_headline`) -------------------

GPU_BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 "GPU_BASELINE.json")


def _gpu_kernel_parity() -> list[dict]:
    """Interpret-mode bit-parity gate for the GPU-lowered kernel bodies:
    run the Triton-shaped lb1/lb2 kernels (``backend="gpu"``,
    ``interpret=True`` — exact on any host, no GPU required) on a random
    ta014 chunk against the jnp oracles the engine trusts.  This is the
    CPU-provable half of the GPU story: a rate banked past a red gate
    would be a number for a different tree.  Returns one row per kernel;
    ``ok`` on every row is the session's go/no-go."""
    import jax.numpy as jnp
    import numpy as np

    from tpu_tree_search.ops import pallas_kernels as PK
    from tpu_tree_search.ops import pfsp_device
    from tpu_tree_search.problems import PFSPProblem

    prob = PFSPProblem(inst=14, lb="lb2", ub=1)
    t = pfsp_device.PFSPDeviceTables(prob.lb1_data, prob.lb2_data)
    n = prob.jobs
    rng = np.random.default_rng(20)
    B = 64
    prmu = jnp.asarray(
        np.stack([rng.permutation(n).astype(np.int32) for _ in range(B)]))
    limit1 = jnp.asarray(rng.integers(-1, n - 1, B).astype(np.int32))
    rows = []

    oracle1 = pfsp_device._lb1_chunk(
        prmu, limit1, t.ptm_t, t.min_heads, t.min_tails)
    got1 = PK.pfsp_lb1_bounds(
        prmu, limit1, t.ptm_t, t.min_heads, t.min_tails,
        interpret=True, backend="gpu")
    rows.append({"kernel": "pfsp_lb1",
                 "ok": bool(np.array_equal(np.asarray(oracle1),
                                           np.asarray(got1)))})

    oracle2 = pfsp_device._lb2_chunk(
        prmu, limit1, t.ptm_t, t.min_heads, t.min_tails,
        t.pairs, t.lags, t.johnson_schedules)
    got2 = PK.pfsp_lb2_bounds(prmu, limit1, t, interpret=True, backend="gpu")
    # Open child slots only — closed slots are garbage by contract.
    open_ = np.arange(n)[None, :] >= np.asarray(limit1)[:, None] + 1
    rows.append({"kernel": "pfsp_lb2",
                 "ok": bool(np.array_equal(np.asarray(oracle2)[open_],
                                           np.asarray(got2)[open_]))})
    return rows


def gpu_headline_main() -> int:
    """``python bench.py gpu_headline``: the GPU flavor of the headline —
    PFSP ta014 lb1 + lb2 and N-Queens under ``TTS_KERNEL_BACKEND=gpu``,
    parity-gated against the same sequential goldens as the TPU bench,
    banked flush-as-you-go into GPU_BASELINE.json with roofline capture
    (TTS_PHASEPROF armed, so ``SearchResult.roofline`` lands in each row).

    Two-stage gate: (1) interpret-mode bit-parity of the GPU-lowered
    kernel bodies vs the jnp oracles — provable on this CPU container,
    red means DO NOT bank; (2) per-row tree/sol/makespan parity of the
    full search.  On a host without a GPU the searches run on whatever
    jax picks (the forced-gpu knob routes policy tables and reporting;
    the Pallas routing stays off off-chip), the artifact is written to
    tempdir (platform "cpu-sim"), and rc=0 still requires every gate
    green — that is the CI arming path for scripts/gpu_session.sh, which
    runs this same entry on a real card and commits the artifact.
    Knobs: TTS_GPU_BASELINE_OUT (artifact path), TTS_GPU_HEADLINE_NQ
    (N-Queens size; default 15 on a GPU, 12 in cpu-sim)."""
    partial = BenchPartial()
    partial.install_sigterm()
    import tempfile as _tempfile

    import jax

    from tpu_tree_search.cli import enable_compile_cache
    from tpu_tree_search.problems import NQueensProblem, PFSPProblem

    enable_compile_cache()
    cpu = os.environ.get("JAX_PLATFORMS", "").startswith("cpu")
    on_gpu = jax.devices()[0].platform == "gpu"
    out = os.environ.get("TTS_GPU_BASELINE_OUT") or (
        GPU_BASELINE_PATH if on_gpu
        else os.path.join(_tempfile.gettempdir(), "GPU_BASELINE.json"))
    doc = {
        "metric": "gpu_headline",
        "commit": _git_head(),
        "contracts": contracts_fingerprint(),
        "platform": "gpu" if on_gpu else ("cpu-sim" if cpu else "non-gpu"),
        "kernel_backend_mode": "gpu",
        "status": "running",
        "kernel_parity": [],
        "records": [],
    }

    def bank() -> None:
        doc["updated"] = time.strftime("%Y-%m-%d %H:%M:%S UTC", time.gmtime())
        tmp = out + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, out)

    bank()
    partial.stage("kernel_parity", "running")
    try:
        doc["kernel_parity"] = _gpu_kernel_parity()
    except Exception as e:  # noqa: BLE001 — the gate must report, not crash
        doc["kernel_parity"] = [{"kernel": "gate",
                                 "ok": False,
                                 "error": f"{type(e).__name__}: {e}"}]
    gate_ok = bool(doc["kernel_parity"]) and all(
        r.get("ok") for r in doc["kernel_parity"])
    partial.stage("kernel_parity", "ok" if gate_ok else "error",
                  rows=doc["kernel_parity"])
    if not gate_ok:
        doc["status"] = "kernel-parity-failed"
        bank()
        print(json.dumps(doc))
        partial.finish(1, "gpu kernel parity gate red")
        return 1

    nq_n = int(os.environ.get("TTS_GPU_HEADLINE_NQ")
               or (15 if on_gpu else 12))
    rows = [
        ("pfsp_ta014_lb1", lambda: PFSPProblem(inst=14, lb="lb1", ub=1),
         25, HEADLINE_M,
         lambda r: (r.explored_tree == GOLDEN_LB1["tree"]
                    and r.explored_sol == GOLDEN_LB1["sol"]
                    and r.best == GOLDEN_LB1["makespan"])),
        ("pfsp_ta014_lb2", lambda: PFSPProblem(inst=14, lb="lb2", ub=1),
         25, 1024 if on_gpu else 4096,
         lambda r: (r.explored_tree == GOLDEN_LB2["tree"]
                    and r.explored_sol == GOLDEN_LB2["sol"]
                    and r.best == GOLDEN_LB2["makespan"])),
        (f"nqueens_n{nq_n}", lambda: NQueensProblem(N=nq_n),
         25, 65536,
         lambda r: r.explored_sol == NQ_SOL.get(nq_n, r.explored_sol)),
    ]
    all_parity = True
    for name, mk, m, M, parity_fn in rows:
        partial.stage(name, "running")
        try:
            # TTS_PHASEPROF arms the phase clocks so res.roofline (the
            # memory-roofline audit, obs/roofline.py) rides each row; the
            # audit resolves its peak through profile_backend, so a forced
            # non-native run reads the honest cpu denominator, never the
            # nominal GPU one.
            with _env_override("TTS_KERNEL_BACKEND", "gpu"), \
                    _env_override("TTS_PHASEPROF", "1"):
                res, nps, elapsed, device_phase = run_config(mk(), m=m, M=M)
            parity = bool(parity_fn(res))
            row = {
                "metric": f"{name}_nodes_per_sec_per_chip",
                "value": round(nps, 1),
                "unit": "nodes/sec",
                "parity": parity,
                "explored_tree": res.explored_tree,
                "explored_sol": res.explored_sol,
                "best": res.best,
                "device_phase_s": round(device_phase, 3),
                "total_s": round(elapsed, 3),
                "kernel_backend": res.kernel_backend,
                "megakernel": res.megakernel,
            }
            if f"{name}" in REF_C_SEQ:
                row["vs_ref_c_seq"] = round(nps / REF_C_SEQ[name], 3)
            if res.megakernel_reason:
                row["megakernel_reason"] = res.megakernel_reason
            if res.roofline is not None:
                row["roofline_mem"] = res.roofline
        except Exception as e:  # noqa: BLE001 — bank the failure, keep going
            parity = False
            row = {"metric": f"{name}_nodes_per_sec_per_chip",
                   "parity": False,
                   "error": f"{type(e).__name__}: {e}"}
        all_parity = all_parity and parity
        doc["records"].append(row)
        bank()
        partial.stage(name, "ok" if parity else "error",
                      value=row.get("value"),
                      **({"error": row["error"]} if row.get("error") else {}))
    doc["status"] = "complete" if all_parity else "parity-failed"
    bank()
    print(json.dumps(doc))
    partial.finish(0 if all_parity else 1,
                   None if all_parity else "search parity gate red")
    return 0 if all_parity else 1


def _main(partial: BenchPartial) -> int:
    from tpu_tree_search.cli import enable_compile_cache

    enable_compile_cache()

    # TTS_BENCH_EXPRESS=1: bank a first on-chip number in the smallest
    # possible window — short liveness, no kernel probes (jnp path, proven
    # on-chip in round 2), headline config only. The hardware session runs
    # this before the full bench so a tunnel that stays up five minutes
    # still produces the round's artifact; a completed full bench then
    # overwrites BENCH_LAST_GOOD.json with the better-configured number.
    express = os.environ.get("TTS_BENCH_EXPRESS", "0") == "1"
    partial.stage("backend_alive", "running", express=express)
    alive, alive_err = backend_alive(120.0 if express else 240.0)
    partial.stage("backend_alive", "ok" if alive else "error",
                  **({} if alive else {"error": alive_err}))
    if not alive:
        err_record = {
            "metric": "pfsp_ta014_lb1_nodes_per_sec_per_chip",
            "value": 0.0,
            "unit": "nodes/sec",
            "vs_baseline": 0.0,
            "parity": False,
            "error": alive_err,
            "contracts": contracts_fingerprint(),
            "pallas": False,
            # The TPU is unreachable, but the host-runtime comparison needs
            # no TPU — an outage round still banks measured numbers.
            # (Express mode skips it: the full bench follows right behind.)
            "extra": [] if express else host_seq_extras(),
        }
        if (lg := last_good()) is not None:
            err_record["last_good"] = lg
        partial.rows_from_extras(err_record["extra"])
        partial.finish(1, f"backend_dead: {alive_err}")
        print(json.dumps(err_record))
        return 1

    if express:
        os.environ["TTS_PALLAS"] = "0"
        pallas_ok = lb2_ok = staged_ok = False
        pallas_err = "express mode: probes skipped (jnp path)"
        lb2_err = staged_err = None
    else:
        partial.stage("pallas_probe", "running")
        (pallas_ok, pallas_err, lb2_ok, lb2_err,
         staged_ok, staged_err) = probe_pallas()
    partial.stage("pallas_probe", "ok" if pallas_ok else "fallback",
                  pallas=pallas_ok, lb2=lb2_ok, staged=staged_ok,
                  **({"error": pallas_err} if pallas_err else {}))
    if not pallas_ok:
        os.environ["TTS_PALLAS"] = "0"
    if pallas_ok and not lb2_ok:
        # lb2-family failure keeps the headline lb1 kernel path: only the
        # lb2 child/self kernels fall back to jnp.
        os.environ["TTS_PALLAS_LB2"] = "0"
    if pallas_ok and lb2_ok and not staged_ok:
        # The lb2 staging is an optimization over the already-correct
        # single-pass kernel path; a PROVEN self-kernel failure costs only
        # that. When the probe never ran (non-TPU, Pallas off) the env is
        # left alone — an explicit TTS_LB2_STAGED=1 (the documented way to
        # exercise staging off-TPU) must not be clobbered.
        os.environ["TTS_LB2_STAGED"] = "0"

    import jax

    from tpu_tree_search.problems import PFSPProblem

    on_tpu = jax.default_backend() == "tpu"
    record: dict = {}

    class _FlushingExtras(list):
        # Every extra row lands in the partial the moment it is measured
        # (flush-as-you-go): a timeout mid-extras keeps the finished rows.
        def append(self, rec):
            super().append(rec)
            partial.rows_from_extras([rec])

        def extend(self, recs):
            for rec in recs:
                self.append(rec)

    extras: list[dict] = _FlushingExtras()
    try:
        prob_hl = PFSPProblem(inst=14, lb="lb1", ub=1)
    except Exception as e:  # noqa: BLE001 — the line must still print
        print(json.dumps({
            "metric": "pfsp_ta014_lb1_nodes_per_sec_per_chip",
            "value": 0.0, "unit": "nodes/sec", "vs_baseline": 0.0,
            "parity": False, "error": f"{type(e).__name__}: {e}",
            "pallas": pallas_ok, "extra": [],
        }))
        return 1
    # Empirical headline-path selection: the probe proves the Pallas lb1
    # kernel CORRECT, not fast — if the jnp/XLA path outruns it on this
    # chip, the headline must use the faster one (both are exact; the
    # metric allows any correct configuration). The kernel microbench on
    # the search's chunk shape decides; its compiles warm the cache the
    # chosen path reuses.
    micro: dict = {}
    headline_path = "jnp" if not pallas_ok else "pallas"
    try:
        if express:
            pass  # no microbench: every compile second counts
        elif on_tpu and pallas_ok:
            # The lb1 family is demoted to jnp by default (TTS_PALLAS=force
            # re-arms it — docs/HW_VALIDATION.md decision record), so the
            # kernel arm of the A/B must force the route explicitly.
            with _env_override("TTS_PALLAS", "force"):
                mb_pallas = eval_microbench(prob_hl, on_tpu)
            with _env_override("TTS_PALLAS", "0"):
                mb_jnp = eval_microbench(prob_hl, on_tpu)
            micro = {"pallas": mb_pallas, "jnp": mb_jnp}
            if (mb_jnp["bound_evals_per_sec"]
                    > mb_pallas["bound_evals_per_sec"]):
                headline_path = "jnp"
            else:
                headline_path = "pallas"
        else:
            micro = {"jnp" if not pallas_ok else "pallas":
                     eval_microbench(prob_hl, on_tpu)}
    except Exception as e:  # noqa: BLE001 — selection is best-effort
        micro = {"error": f"{type(e).__name__}: {e}"}
    # Host-event trace of the headline run (TTS_OBS=host: host tracing
    # only, device programs stay byte-identical — the measurement is NOT
    # perturbed; docs/OBSERVABILITY.md). An explicit TTS_OBS is respected.
    from tpu_tree_search.obs import events as obs_events

    _obs_prev = os.environ.get("TTS_OBS")
    if _obs_prev is None:
        os.environ["TTS_OBS"] = "host"
    obs_events.reset()
    partial.stage("headline", "running")
    try:
        # -- headline: PFSP ta014 lb1 --------------------------------------
        # A jnp demotion is scoped to THIS run: the lb2/nqueens extras have
        # their own kernels, which the lb1 microbench says nothing about.
        def _headline_run():
            if headline_path == "jnp" and pallas_ok:
                with _env_override("TTS_PALLAS", "0"):
                    return run_config(prob_hl, m=25, M=HEADLINE_M)
            if headline_path == "pallas":
                # Demoted-by-default lb1 kernels need the force spelling.
                with _env_override("TTS_PALLAS", "force"):
                    return run_config(prob_hl, m=25, M=HEADLINE_M)
            return run_config(prob_hl, m=25, M=HEADLINE_M)

        compact_stats = None
        best_run = None
        if on_tpu and not express:
            # Empirical compaction pick (cf. the jnp-vs-Pallas pick above):
            # scatter serializes on TPU, sort loses on CPU, dense is the
            # shift-based fast path — measure each on the production
            # config, bank the winner, record all plus the per-mode cycle
            # decomposition (evaluator vs maintenance). One problem
            # instance is fine: the program cache keys on the routing
            # token, which includes TTS_COMPACT.
            from tpu_tree_search.ops.compaction import resolve_compact_mode

            compact_stats, best_run = pick_compact(
                _headline_run,
                lambda r: (r[0].explored_tree == GOLDEN_LB1["tree"]
                           and r[0].explored_sol == GOLDEN_LB1["sol"]
                           and r[0].best == GOLDEN_LB1["makespan"]),
                budget_s=600.0,
                eval_ms=eval_cycle_ms(prob_hl, 25, HEADLINE_M),
                auto_mode=resolve_compact_mode(
                    prob_hl, HEADLINE_M, prob_hl.jobs, jax.devices()[0]
                ),
                phase_probe=(
                    (lambda: phase_split_probe(prob_hl, 25, HEADLINE_M))
                    if _phaseprof_armed() else None
                ),
            )
        if best_run is not None:
            res, nps, elapsed, device_phase = best_run
        else:
            res, nps, elapsed, device_phase = _headline_run()
        parity = (
            res.explored_tree == GOLDEN_LB1["tree"]
            and res.explored_sol == GOLDEN_LB1["sol"]
            and res.best == GOLDEN_LB1["makespan"]
        )
        record = {
            "metric": "pfsp_ta014_lb1_nodes_per_sec_per_chip",
            "value": round(nps, 1),
            "unit": "nodes/sec",
            "vs_baseline": round(nps / REFERENCE_NODES_PER_SEC, 3),
            "vs_ref_c_seq": round(nps / REF_C_SEQ["pfsp_ta014_lb1"], 3),
            "vs_ref_c_lb1d": round(nps / REF_C_SEQ["pfsp_ta014_lb1_d"], 3),
            "parity": parity,
            "explored_tree": res.explored_tree,
            "explored_sol": res.explored_sol,
            "makespan": res.best,
            "device_phase_s": round(device_phase, 3),
            "total_s": round(elapsed, 3),
            "kernel_launches": res.diagnostics.kernel_launches,
            # One-kernel cycle provenance: the resolved TTS_MEGAKERNEL
            # state the headline number ran under (and, when the resolver
            # declined/refused, why) — a banked rate is meaningless
            # without knowing which cycle body produced it.
            "megakernel": res.megakernel,
            "roofline": roofline(nps, prob_hl.jobs, prob_hl.machines, None,
                                 "lb1", problem=prob_hl),
        }
        if res.megakernel_reason:
            record["megakernel_reason"] = res.megakernel_reason
        if res.megakernel_mt:
            record["megakernel_mt"] = res.megakernel_mt
            record["megakernel_tiled"] = res.megakernel_tiled
        if res.roofline is not None:
            # Memory-roofline audit (obs/roofline.py) — distinct from the
            # FLOP-MFU "roofline" key above: per-phase %-of-memory-bound
            # peak when the headline ran phase-profiled.
            record["roofline_mem"] = res.roofline
        if compact_stats is not None:
            record["compact"] = compact_stats
        # Measured kernel-only throughput on the same chunk shape: the
        # roofline's empirical cross-check (search MFU << kernel MFU means
        # the gap is orchestration, not the kernel) — and the basis of the
        # headline-path selection above.
        record["kernel_microbench"] = micro
        record["headline_eval_path"] = headline_path
    except Exception as e:  # noqa: BLE001 — the line must still print
        record = {
            "metric": "pfsp_ta014_lb1_nodes_per_sec_per_chip",
            "value": 0.0,
            "unit": "nodes/sec",
            "vs_baseline": 0.0,
            "parity": False,
            "error": f"{type(e).__name__}: {e}",
        }
    partial.stage(
        "headline",
        "ok" if record.get("parity") else "error",
        value=record.get("value"),
        **({"error": record["error"]} if record.get("error") else {}),
    )
    # Attach the headline trace artifact (never fatal): Perfetto-loadable
    # file next to the bench, summary riding the JSON line.
    hl_events = obs_events.drain()
    if _obs_prev is None:
        os.environ.pop("TTS_OBS", None)
    try:
        import tempfile

        from tpu_tree_search.obs import export as obs_export
        from tpu_tree_search.obs import report as obs_report

        # Committed artifact only for real on-chip runs (the
        # BENCH_LAST_GOOD.json policy); CPU smoke runs — including the
        # express e2e test — must not dirty the working tree.
        trace_dir = (
            os.path.dirname(LAST_GOOD_PATH) if on_tpu
            else tempfile.gettempdir()
        )
        trace_path = os.path.join(trace_dir, "BENCH_TRACE.json")
        n_ev = obs_export.write_chrome_trace(
            hl_events, trace_path, label="bench-headline"
        )
        record["trace"] = {
            "path": os.path.basename(trace_path) if on_tpu else trace_path,
            "events": n_ev,
            "span_s": obs_report.summarize(hl_events)["span_s"],
        }
    except Exception:  # noqa: BLE001 — bookkeeping must not cost the line
        pass
    # Cost-model capture from the same headline events (on-chip only — a
    # CPU smoke fit would pace real controllers with nonsense): measured
    # dispatch latency+bandwidth lands in COSTMODEL.json next to the
    # bench, where TTS_COSTMODEL can arm it (docs/OBSERVABILITY.md).
    if on_tpu:
        try:
            from tpu_tree_search.obs import costmodel as obs_costmodel

            prob_cm = PFSPProblem(inst=14, lb="lb1", ub=1)
            profile = obs_costmodel.build_profile(
                hl_events, "tpu", "device-D1",
                obs_costmodel.shape_class(prob_cm),
            )
            cm_path = os.path.join(os.path.dirname(LAST_GOOD_PATH),
                                   "COSTMODEL.json")
            obs_costmodel.save(cm_path, profile)
            record["costmodel"] = {
                "path": os.path.basename(cm_path),
                "links": sorted(next(iter(profile.values()))["links"]),
            }
        except Exception:  # noqa: BLE001 — capture must not cost the line
            pass

    # -- extras: ta014 lb2 + N-Queens N=15 (never fail the bench; express
    # mode skips them all and shares the finalization tail below) ----------
    if not express:
        _collect_extras(extras, on_tpu, staged_ok, staged_err)
        # Dispatch-latency microbench: K=1 vs K=max × depth 1 vs 2 rows +
        # the headline pipeline on/off A/B (TPU) and the simulated-latency
        # CPU harness row (every backend).
        _dispatch_latency_rows(extras, on_tpu)
        # Instance-batching A/B: serial vs batched_search at B in
        # {1, 4, 8}, bit-identity checked per job (CPU-sim, every
        # backend — the --batch-slots evidence row).
        _batch_ab_rows(extras)
        # One-kernel-cycle A/B: interpret parity gate on every backend,
        # timed off-vs-force ta014 lb1 rows on TPU (the keep/retire
        # evidence, docs/HW_VALIDATION.md).
        _megakernel_ab_rows(extras, on_tpu)
        # Hierarchical-stealing A/B: flat vs hier on the virtual-host
        # simulated-latency harness, parity-gated on node counts
        # (CPU-sim, every backend — the TTS_STEAL evidence row).
        _steal_ab_rows(extras)
        # Narrow-node-storage A/B: bytes/node, prmu row, checkpoint and
        # snapshot payload sizes narrow-vs-wide on ta014, parity-gated on
        # a reduced-instance search (the TTS_NARROW evidence row).
        _bytes_ab_rows(extras)
    # Published-config rate rows run in BOTH modes (bounded — a few
    # dispatches each), so any green window banks a first ta021/N16/N17
    # number automatically.
    _published_rate_rows(extras, on_tpu)
    if express:
        record["express"] = True
    record["backend"] = jax.default_backend()
    # Provenance: the contract fingerprint this number was measured under
    # (ties the row to the exact compiled-program structure — ISSUE 8).
    record["contracts"] = contracts_fingerprint()
    record["pallas"] = pallas_ok
    if pallas_err:
        record["pallas_error"] = pallas_err
    record["pallas_lb2"] = lb2_ok
    if lb2_err:
        record["pallas_lb2_error"] = lb2_err
    record["extra"] = extras
    if on_tpu and record.get("parity") and record.get("value", 0) > 0:
        record_last_good(record)
    rc = 0 if record.get("parity") else 1
    partial.rows_from_extras(extras)
    partial.finish(rc)
    print(json.dumps(record))
    return rc


def _published_rate_rows(extras: list, on_tpu: bool) -> None:
    """First measured numbers for the published BASELINE configs 2 and 4
    (N-Queens N=16/17 and ta021 lb2 — VERDICT r5 #5): their full searches
    are minutes-to-hours at current rates, so these are BOUNDED-dispatch
    rate rows — ``max_steps`` cuts after a few K-cycle dispatches and the
    metric is device-phase nodes/s (golden-count parity is not computable
    on a cutoff; ``complete`` records whether the run happened to finish).
    On-TPU only: CPU smoke must not pay minutes for rate rows that mean
    nothing off-chip. One warm dispatch per config compiles via the
    persistent cache (scripts/warm_cache.py banks the same shapes)."""
    if not on_tpu:
        return
    from tpu_tree_search.engine.resident import resident_search
    from tpu_tree_search.problems import NQueensProblem, PFSPProblem

    configs = [
        ("pfsp_ta021_lb2_nodes_per_sec_per_chip_bounded",
         lambda: PFSPProblem(inst=21, lb="lb2", ub=1), 1024, 4),
        ("nqueens_n16_nodes_per_sec_per_chip_bounded",
         lambda: NQueensProblem(N=16), 65536, 4),
        ("nqueens_n17_nodes_per_sec_per_chip_bounded",
         lambda: NQueensProblem(N=17), 65536, 4),
    ]
    for metric, mk, M, steps in configs:
        try:
            resident_search(mk(), m=25, M=M, max_steps=1)  # compile + warm
            res = resident_search(mk(), m=25, M=M, max_steps=steps)
            device_phase = (res.phases[1].seconds if len(res.phases) > 1
                            else res.elapsed)
            extras.append({
                "metric": metric,
                "value": round(res.explored_tree / max(device_phase, 1e-9), 1),
                "unit": "nodes/sec",
                "bounded_steps": steps,
                "explored_tree": res.explored_tree,
                "complete": res.complete,
            })
        except Exception as e:  # noqa: BLE001 — rate rows never fail a bench
            extras.append({
                "metric": metric, "error": f"{type(e).__name__}: {e}",
            })


def _collect_extras(extras: list, on_tpu: bool, staged_ok: bool,
                    staged_err: str | None) -> None:
    """The full bench's extra records (ta014 lb2 + staged comparison,
    N-Queens, host-seq) — split out so the express path shares main()'s
    single finalization tail instead of duplicating it."""
    from tpu_tree_search.problems import NQueensProblem, PFSPProblem

    try:
        # Chunk size measured on the real v5e (scripts/lb2_tune.py, round
        # 5): like the headline, per-cycle cost scales with M while the
        # heavily-pruned lb2 frontier rarely fills big chunks — staged
        # M=1024 ran 158.8k nodes/s (2.43x ref C) vs 50.7k at the old
        # 65536. CPU smoke keeps moderate chunks (jnp lb2's per-pair
        # intermediates dominate there).
        lb2_m, lb2_M = 25, (1024 if on_tpu else 4096)

        def _lb2_run():
            return run_config(
                PFSPProblem(inst=14, lb="lb2", ub=1), m=lb2_m, M=lb2_M
            )

        lb2_compact, lb2_best = None, None
        if on_tpu:
            # Same empirical compaction pick as the headline — lb2 runs are
            # ~1s each at the tuned chunk size, so the A/B is nearly free.
            from tpu_tree_search.ops.compaction import resolve_compact_mode

            _p2 = PFSPProblem(inst=14, lb="lb2", ub=1)
            lb2_compact, lb2_best = pick_compact(
                _lb2_run,
                lambda r: (r[0].explored_tree == GOLDEN_LB2["tree"]
                           and r[0].explored_sol == GOLDEN_LB2["sol"]
                           and r[0].best == GOLDEN_LB2["makespan"]),
                budget_s=300.0,
                eval_ms=eval_cycle_ms(_p2, lb2_m, lb2_M),
                auto_mode=resolve_compact_mode(_p2, lb2_M, _p2.jobs),
                phase_probe=(
                    (lambda: phase_split_probe(_p2, lb2_m, lb2_M))
                    if _phaseprof_armed() else None
                ),
            )
        if lb2_best is not None:
            res2, nps2, _, _ = lb2_best
        else:
            res2, nps2, _, _ = _lb2_run()
        staged_speedup = None
        if staged_ok and os.environ.get("TTS_LB2_STAGED", "auto") != "0":
            # Measure the incumbent-staging win directly (VERDICT r3 #4):
            # the same config with staging forced off, on a fresh problem
            # (resident programs cache per instance + env knob). Its own
            # try/except: a failure here must not discard the
            # already-measured primary lb2 record; the env override is
            # restored, never popped (bench must not eat a user's explicit
            # TTS_LB2_STAGED).
            try:
                # Same compaction mode as the primary measurement — the
                # speedup must isolate staging, not mix compaction modes.
                with _env_override("TTS_LB2_STAGED", "0"), \
                        _compact_ctx(lb2_compact):
                    _, nps2_off, _, _ = _lb2_run()
                staged_speedup = round(nps2 / max(nps2_off, 1e-9), 3)
            except Exception:  # noqa: BLE001 — comparison is best-effort
                staged_speedup = None
        extras.append({
            "metric": "pfsp_ta014_lb2_nodes_per_sec_per_chip",
            "value": round(nps2, 1),
            "vs_ref_c_seq": round(nps2 / REF_C_SEQ["pfsp_ta014_lb2"], 3),
            "parity": (
                res2.explored_tree == GOLDEN_LB2["tree"]
                and res2.explored_sol == GOLDEN_LB2["sol"]
                and res2.best == GOLDEN_LB2["makespan"]
            ),
            "explored_tree": res2.explored_tree,
            "makespan": res2.best,
            "staged": os.environ.get("TTS_LB2_STAGED", "auto") == "1"
            or (staged_ok
                and os.environ.get("TTS_LB2_STAGED", "auto") != "0"),
            **({"staged_error": staged_err} if staged_err else {}),
            **({"staged_speedup": staged_speedup}
               if staged_speedup is not None else {}),
            **({"compact": lb2_compact} if lb2_compact else {}),
        })
    except Exception as e:  # noqa: BLE001
        extras.append({
            "metric": "pfsp_ta014_lb2_nodes_per_sec_per_chip",
            "error": f"{type(e).__name__}: {e}",
        })
    N = 15 if on_tpu else 12  # CPU smoke stays fast
    try:
        # N-Queens cycles are compaction-bound (no pruning: every cycle
        # compacts a full M*n grid, and XLA:TPU serializes the scatter), so
        # the compaction mode matters MOST here. The N=15 tree costs ~60s a
        # run — too dear to A/B directly — so probe the modes on N=14
        # (~27M nodes) and run N=15 once with the winner; a probe failure
        # costs the probe, never the N=15 record.
        nq_compact = None
        if on_tpu:
            from tpu_tree_search.ops.compaction import resolve_compact_mode

            _pq = NQueensProblem(N=14)
            nq_compact, _ = pick_compact(
                lambda: run_config(NQueensProblem(N=14), m=25, M=65536),
                lambda r: r[0].explored_sol == NQ_SOL[14],
                budget_s=420.0,
                eval_ms=eval_cycle_ms(_pq, 25, 65536, cycles=16),
                auto_mode=resolve_compact_mode(_pq, 65536, _pq.N),
                phase_probe=(
                    (lambda: phase_split_probe(_pq, 25, 65536, K=16))
                    if _phaseprof_armed() else None
                ),
            )
            if nq_compact is not None:
                # The stats were measured on the PROBE config, not N=15 —
                # make the artifact self-describing.
                nq_compact["probe"] = "nqueens_n14"
        with _compact_ctx(nq_compact):
            resq, npsq, _, _ = run_config(NQueensProblem(N=N), m=25, M=65536)
        extras.append({
            "metric": f"nqueens_n{N}_nodes_per_sec_per_chip",
            "value": round(npsq, 1),
            **({"vs_ref_c_seq": round(npsq / REF_C_SEQ[f"nqueens_n{N}"], 3)}
               if f"nqueens_n{N}" in REF_C_SEQ else {}),
            "parity": resq.explored_sol == NQ_SOL[N],
            "explored_tree": resq.explored_tree,
            "explored_sol": resq.explored_sol,
            **({"compact": nq_compact} if nq_compact else {}),
        })
    except Exception as e:  # noqa: BLE001
        extras.append({
            "metric": f"nqueens_n{N}_nodes_per_sec_per_chip",
            "error": f"{type(e).__name__}: {e}",
        })

    extras.extend(host_seq_extras())


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "fleet_sat":
        sys.exit(fleet_sat_main())
    if len(sys.argv) > 1 and sys.argv[1] == "gpu_headline":
        sys.exit(gpu_headline_main())
    sys.exit(main())
