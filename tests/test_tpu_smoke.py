"""Hardware compile gate for the Pallas kernels.

Interpret-mode tests (tests/test_pallas_kernels.py) validate the kernel
*math* but never execute Mosaic lowering, so a kernel that cannot compile
for the real TPU backend can hide behind a green CPU suite (this is exactly
what happened in rounds 1-2). These tests compile each kernel for the real
backend and check bit-equality against the jnp oracles on the open child
slots — run them on any TPU machine with::

    TTS_TPU_TESTS=1 python -m pytest tests/test_tpu_smoke.py -v

They skip (not pass) everywhere else. The bench harness exercises the same
compile path implicitly; this file makes it a first-class test.
"""

from __future__ import annotations

import numpy as np
import pytest

jax = pytest.importorskip("jax")

pytestmark = pytest.mark.skipif(
    jax.default_backend() != "tpu", reason="needs a real TPU backend"
)


@pytest.fixture(scope="module")
def pfsp14():
    from tpu_tree_search.ops import pfsp_device as P
    from tpu_tree_search.problems import PFSPProblem

    prob = PFSPProblem(inst=14, lb="lb1", ub=1)
    tables = P.PFSPDeviceTables(prob.lb1_data, prob.lb2_data)
    rng = np.random.default_rng(7)
    B = 256
    prmu = np.tile(np.arange(prob.jobs, dtype=np.int32), (B, 1))
    for i in range(B):
        rng.shuffle(prmu[i])
    limit1 = rng.integers(-1, prob.jobs - 1, size=B).astype(np.int32)
    open_ = np.arange(prob.jobs)[None, :] >= (limit1[:, None] + 1)
    return prob, tables, prmu, limit1, open_


def test_nqueens_kernel_compiles_on_tpu():
    import jax.numpy as jnp

    from tpu_tree_search.ops import nqueens_device, pallas_kernels as PK

    N = 14
    rng = np.random.default_rng(3)
    B = 128
    board = np.tile(np.arange(N, dtype=np.uint8), (B, 1))
    for i in range(B):
        rng.shuffle(board[i])
    depth = rng.integers(0, N, size=B).astype(np.int32)
    got = np.asarray(
        PK.nqueens_labels(jnp.asarray(board), jnp.asarray(depth), N)
    )
    ref = np.asarray(
        nqueens_device.make_core(N)(jnp.asarray(board), jnp.asarray(depth))
    )
    np.testing.assert_array_equal(got, ref)


def test_lb1_kernel_compiles_on_tpu(pfsp14):
    import jax.numpy as jnp

    from tpu_tree_search.ops import pfsp_device as P, pallas_kernels as PK

    prob, t, prmu, limit1, open_ = pfsp14
    prmu_d, l1_d = jnp.asarray(prmu), jnp.asarray(limit1)
    got = np.asarray(
        PK.pfsp_lb1_bounds(prmu_d, l1_d, t.ptm_t, t.min_heads, t.min_tails)
    )
    ref = np.asarray(
        P._lb1_chunk(prmu_d, l1_d, t.ptm_t, t.min_heads, t.min_tails)
    )
    np.testing.assert_array_equal(got[open_], ref[open_])


def test_lb2_kernel_compiles_on_tpu(pfsp14):
    import jax.numpy as jnp

    from tpu_tree_search.ops import pfsp_device as P, pallas_kernels as PK

    prob, t, prmu, limit1, open_ = pfsp14
    prmu_d, l1_d = jnp.asarray(prmu), jnp.asarray(limit1)
    got = np.asarray(PK.pfsp_lb2_bounds(prmu_d, l1_d, t))
    ref = np.asarray(
        P._lb2_chunk(
            prmu_d, l1_d, t.ptm_t, t.min_heads, t.min_tails,
            t.pairs, t.lags, t.johnson_schedules,
        )
    )
    np.testing.assert_array_equal(got[open_], ref[open_])


def test_lb1_d_kernel_compiles_on_tpu(pfsp14):
    import jax.numpy as jnp

    from tpu_tree_search.ops import pfsp_device as P, pallas_kernels as PK

    prob, t, prmu, limit1, open_ = pfsp14
    prmu_d, l1_d = jnp.asarray(prmu), jnp.asarray(limit1)
    got = np.asarray(
        PK.pfsp_lb1_d_bounds(prmu_d, l1_d, t.ptm_t, t.min_heads, t.min_tails)
    )
    ref = np.asarray(
        P._lb1_d_chunk(prmu_d, l1_d, t.ptm_t, t.min_heads, t.min_tails)
    )
    np.testing.assert_array_equal(got[open_], ref[open_])


def test_lb2_self_kernel_compiles_on_tpu(pfsp14):
    """The staged evaluator's second stage: compile + parity on the active
    prefix, plus the n_active tile gating on real Mosaic."""
    import jax.numpy as jnp

    from tpu_tree_search.ops import pfsp_device as P, pallas_kernels as PK

    prob, t, prmu, limit1, _ = pfsp14
    l1 = np.maximum(limit1, 0)  # self rows always have limit1 >= 0
    prmu_d, l1_d = jnp.asarray(prmu), jnp.asarray(l1)
    ref = np.asarray(P._lb2_self_chunk(
        prmu_d, l1_d, t.ptm_t, t.min_heads, t.min_tails,
        t.pairs, t.lags, t.johnson_schedules,
    ))
    for n_active in (prmu.shape[0], 57):
        got = np.asarray(
            PK.pfsp_lb2_self_bounds(prmu_d, l1_d, n_active, t)
        )
        np.testing.assert_array_equal(got[:n_active], ref[:n_active])


def test_mesh_staged_lb2_runs_on_tpu(monkeypatch):
    """The combination the CPU suite cannot reach: the staged lb2
    evaluator (compaction + pl.when-gated self kernel with its traced
    n_active scalar) INSIDE shard_map on real Mosaic — the default mesh
    path for lb2/mp=1 on TPU. TTS_LB2_STAGED=1 pins the path under test
    (an exported =0 or a future auto-gate change must not silently turn
    this into a single-pass run). Reduced instance keeps the wall-clock
    down; parity against the sequential count is exact."""
    from tpu_tree_search.engine.sequential import sequential_search
    from tpu_tree_search.parallel.resident_mesh import mesh_resident_search
    from tpu_tree_search.problems import PFSPProblem
    from tpu_tree_search.problems.pfsp import taillard

    monkeypatch.setenv("TTS_LB2_STAGED", "1")

    ptm = taillard.reduced_instance(14, jobs=10, machines=5)
    opt = sequential_search(PFSPProblem(lb="lb2", ub=0, p_times=ptm)).best
    seq = sequential_search(
        PFSPProblem(lb="lb2", ub=0, p_times=ptm), initial_best=opt
    )
    res = mesh_resident_search(
        PFSPProblem(lb="lb2", ub=0, p_times=ptm), m=8, M=128, K=8,
        initial_best=opt,
    )
    assert (res.explored_tree, res.explored_sol, res.best) == (
        seq.explored_tree, seq.explored_sol, opt
    )


def test_lb2_self_mp_sliced_kernel_compiles_on_tpu(pfsp14):
    """The mp-staged path's kernel variant — the self kernel over a SLICED
    pair block (P_local tables instead of the full set) — on real Mosaic,
    and the pmax-combine identity: per-shard maxes must equal the full-pair
    self bound. (The shard_map composition itself is CPU-mesh-tested; the
    single real chip cannot host an mp=2 mesh, but the compile risk lives
    entirely in the sliced kernel call.)"""
    import jax.numpy as jnp

    from tpu_tree_search.ops import pfsp_device as P, pallas_kernels as PK

    prob, t, prmu, limit1, _ = pfsp14
    l1 = np.maximum(limit1, 0)
    prmu_d, l1_d = jnp.asarray(prmu), jnp.asarray(l1)
    ref = np.asarray(P._lb2_self_chunk(
        prmu_d, l1_d, t.ptm_t, t.min_heads, t.min_tails,
        t.pairs, t.lags, t.johnson_schedules,
    ))
    mp_size = 2
    P_pad = -(-t.pairs.shape[0] // mp_size) * mp_size
    P_local = P_pad // mp_size
    ordered = t.johnson_ordered_mp(mp_size)
    parts = [
        np.asarray(PK.pfsp_lb2_self_bounds_tables(
            prmu_d, l1_d, prmu.shape[0], t.ptm_t,
            P._OrderedSlice(ordered, shard * P_local, P_local),
            bf16=t.exact_bf16,
        ))
        for shard in range(mp_size)
    ]
    np.testing.assert_array_equal(np.maximum.reduce(parts), ref)


def _random_large(prob, B, seed):
    rng = np.random.default_rng(seed)
    prmu = np.stack(
        [rng.permutation(prob.jobs).astype(np.int32) for _ in range(B)]
    )
    limit1 = rng.integers(-1, prob.jobs - 1, B).astype(np.int32)
    open_ = np.arange(prob.jobs)[None, :] >= (limit1[:, None] + 1)
    return prmu, limit1, open_


@pytest.mark.parametrize(
    "inst,lb,B",
    [
        (31, "lb1", 64),   # 50 x 10
        (56, "lb1", 32),   # 50 x 20
        (56, "lb2", 16),   # 50 x 20, P=190 pairs
        (111, "lb1", 16),  # 500 x 20
    ],
)
def test_large_instance_kernels_compile_on_tpu(inst, lb, B):
    """Large Taillard classes through the real Mosaic compiler: the
    autoscaled tile must survive hardware, not just the interpret-mode VMEM
    model (the reference instead rebuilds with bigger compile-time params,
    `Taillard.chpl:29-52`). Skips — visibly — when the feasibility gate
    routes the shape to the jnp path (then the gate IS the product
    behavior being validated)."""
    import jax.numpy as jnp

    from tpu_tree_search.ops import pfsp_device as P, pallas_kernels as PK
    from tpu_tree_search.problems import PFSPProblem

    prob = PFSPProblem(inst=inst, lb=lb, ub=1)
    t = P.PFSPDeviceTables(prob.lb1_data, prob.lb2_data)
    n, m = prob.jobs, prob.machines
    if lb == "lb2" and not (n <= 100 and PK.lb2_kernel_feasible(
            n, m, t.pairs.shape[0])):
        pytest.skip(f"gate routes ta{inst:03d} lb2 to the jnp path")
    if lb == "lb1" and not (n <= 512 and PK.lb1_kernel_feasible(n, m)):
        pytest.skip(f"gate routes ta{inst:03d} lb1 to the jnp path")
    prmu, limit1, open_ = _random_large(prob, B, seed=11 + inst)
    prmu_d, l1_d = jnp.asarray(prmu), jnp.asarray(limit1)
    if lb == "lb1":
        got = np.asarray(PK.pfsp_lb1_bounds(
            prmu_d, l1_d, t.ptm_t, t.min_heads, t.min_tails,
            bf16=t.exact_bf16,
        ))
        ref = np.asarray(P._lb1_chunk(
            prmu_d, l1_d, t.ptm_t, t.min_heads, t.min_tails,
            bf16=t.exact_bf16,
        ))
    else:
        got = np.asarray(PK.pfsp_lb2_bounds(prmu_d, l1_d, t))
        ref = np.asarray(P._lb2_chunk(
            prmu_d, l1_d, t.ptm_t, t.min_heads, t.min_tails,
            t.pairs, t.lags, t.johnson_schedules, bf16=t.exact_bf16,
        ))
    np.testing.assert_array_equal(got[open_], ref[open_])


@pytest.mark.parametrize("mode", ["scatter", "sort", "search", "dense"])
def test_compact_modes_on_tpu(mode, monkeypatch):
    """All four TTS_COMPACT rank inversions through the real XLA:TPU
    lowering (sort/search/dense are plain XLA ops — no Mosaic — but their
    TPU lowerings must produce the same exact counts the CPU suite pins;
    the scatter row doubles as the serialized-scatter baseline and the
    dense row proves the shift-compaction fast path on chip)."""
    from tpu_tree_search.engine.resident import resident_search
    from tpu_tree_search.engine.sequential import sequential_search
    from tpu_tree_search.problems import PFSPProblem
    from tpu_tree_search.problems.pfsp import taillard

    monkeypatch.setenv("TTS_COMPACT", mode)
    ptm = taillard.reduced_instance(14, jobs=10, machines=5)
    opt = sequential_search(PFSPProblem(lb="lb1", ub=0, p_times=ptm)).best
    seq = sequential_search(
        PFSPProblem(lb="lb1", ub=0, p_times=ptm), initial_best=opt
    )
    res = resident_search(
        PFSPProblem(lb="lb1", ub=0, p_times=ptm), m=8, M=128, initial_best=opt
    )
    assert (res.explored_tree, res.explored_sol, res.best) == (
        seq.explored_tree, seq.explored_sol, opt
    )
