"""Instance-axis batching (engine/batched.py + serve/batch.py): B-slot
batched programs whose per-slot results are bit-identical to solo runs,
zero-recompile slot splices at dispatch boundaries, per-slot quantum /
cancel / budget semantics in the daemon, and cross-daemon checkpoint
migration (`tts migrate`).

Everything runs on the virtual CPU platform with small shapes; daemons
under test are in-process (port 0). Batch tests submit every job BEFORE
starting the scheduler workers: batch formation requires a same-class
peer at the queue head, and pre-queued jobs make the session shape
deterministic."""

from __future__ import annotations

import json
import threading
import time
import urllib.request

import pytest

from tpu_tree_search.serve.server import ServeDaemon

_FINAL = ("done", "failed", "cancelled")

# One small shape shared across the daemon batch tests (fixed K: the
# batch path requires it — an AdaptiveK job routes solo).
NQ10K4 = {"problem": "nqueens", "N": 10, "M": 256, "K": 4}


def _post(base, path, payload):
    req = urllib.request.Request(
        base + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode())


def _get(base, path):
    try:
        with urllib.request.urlopen(base + path, timeout=30) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode())


def _wait_final(base, jid, timeout_s=180.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        code, rec = _get(base, f"/job/{jid}")
        assert code == 200, rec
        if rec["state"] in _FINAL:
            return rec
        time.sleep(0.1)
    raise AssertionError(f"job {jid} did not finish in {timeout_s}s")


def _wait_state(base, jid, state, timeout_s=60.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        code, rec = _get(base, f"/job/{jid}")
        assert code == 200, rec
        if rec["state"] == state:
            return rec
        assert rec["state"] not in _FINAL, rec
        time.sleep(0.02)
    raise AssertionError(f"job {jid} never reached {state!r}")


def _start_http_only(d):
    """Serve the HTTP API without workers, so submitted jobs stay queued
    until `d.scheduler.start()` (same trick as the admission-control
    test in test_serve.py)."""
    d._http_thread = threading.Thread(
        target=d._httpd.serve_forever, kwargs={"poll_interval": 0.2},
        daemon=True)
    d._http_thread.start()


def _reference(N, M, K, **kw):
    """Standalone resident_search on a FRESH problem (what a one-shot
    `tts run` computes)."""
    from tpu_tree_search.engine.resident import resident_search
    from tpu_tree_search.problems import NQueensProblem

    return resident_search(NQueensProblem(N=N), m=25, M=M, K=K, **kw)


def _counts(rec):
    return (rec["result"]["explored_tree"], rec["result"]["explored_sol"],
            rec["result"]["best"])


# -- engine level ------------------------------------------------------------


def test_batched_contracts_clean():
    """The two pinned contracts: B=1 jaxpr byte-identity vs the solo
    resident step, and make_slot avals == the compiled step's per-slot
    input avals (the zero-recompile splice guarantee), at B in {1, 2}."""
    from tpu_tree_search.analysis.program_audit import (
        audit_batched, load_contracts,
    )

    load_contracts()
    assert audit_batched() == []


def test_engine_batched_bit_identity_and_refill():
    """Every job through a B-slot program lands the solo counts exactly —
    including n_jobs > B, which exercises retire-and-refill (a finished
    slot's frozen ballast replaced by a fresh tenant)."""
    from tpu_tree_search.engine.batched import batched_search
    from tpu_tree_search.engine.resident import resident_search
    from tpu_tree_search.problems import NQueensProblem

    ref = resident_search(NQueensProblem(N=9), m=5, M=64, K=8)
    golden = (ref.explored_tree, ref.explored_sol, ref.best)
    for B, n_jobs in ((1, 2), (2, 5)):
        results = batched_search(NQueensProblem(N=9), n_jobs=n_jobs, B=B,
                                 m=5, M=64, K=8)
        assert len(results) == n_jobs
        for r in results:
            assert (r.explored_tree, r.explored_sol, r.best) == golden
            assert r.complete


def test_engine_batched_obs_counters(monkeypatch):
    """TTS_OBS=1 through the batched program: per-slot counter blocks are
    harvested without perturbing any count."""
    monkeypatch.setenv("TTS_OBS", "1")
    from tpu_tree_search.engine.batched import batched_search
    from tpu_tree_search.engine.resident import resident_search
    from tpu_tree_search.problems import NQueensProblem

    ref = resident_search(NQueensProblem(N=8), m=5, M=64, K=4)
    for r in batched_search(NQueensProblem(N=8), n_jobs=3, B=2,
                            m=5, M=64, K=4):
        assert (r.explored_tree, r.explored_sol) == (
            ref.explored_tree, ref.explored_sol)
        assert r.obs and "device_counters" in r.obs


# -- daemon level ------------------------------------------------------------


def test_daemon_batch_bit_identity_and_zero_recompile_splice(
    tmp_path, monkeypatch
):
    """The tentpole acceptance: three same-class jobs through a 2-slot
    batch under TTS_GUARD=1 — every result bit-identical to solo, the
    first job pays the one batched-program compile, and every SPLICED job
    compiles NOTHING (program + jit cache deltas both zero)."""
    monkeypatch.setenv("TTS_GUARD", "1")
    ref = _reference(N=10, M=256, K=4)
    d = ServeDaemon(port=0, state_dir=str(tmp_path / "state"),
                    batch_slots=2)
    _start_http_only(d)
    try:
        base = d.url
        ids = [_post(base, "/submit", NQ10K4)[1]["id"] for _ in range(3)]
        d.scheduler.start()
        recs = [_wait_final(base, jid) for jid in ids]
        for rec in recs:
            assert rec["state"] == "done", rec.get("error")
            assert _counts(rec) == (ref.explored_tree, ref.explored_sol,
                                    ref.best)
        assert recs[0]["new_programs"] >= 1  # cold class compiled once
        for rec in recs[1:]:
            assert rec["new_programs"] == 0
            assert rec["new_step_compiles"] == 0
        # A finished job has no checkpoint to serve.
        code, err = _get(base, f"/job/{ids[0]}/checkpoint")
        assert code == 409

        # Batch telemetry landed on every surface.
        from tpu_tree_search.serve.metrics import parse_text

        with urllib.request.urlopen(base + "/metrics", timeout=30) as r:
            m = parse_text(r.read().decode())
        assert m["tts_serve_batch_slots"][()] == 2.0
        assert sum(m["tts_serve_slots_spliced_total"].values()) >= 3
        assert sum(m["tts_serve_slots_retired_total"].values()) >= 3
        assert sum(m["tts_serve_batch_efficiency_count"].values()) >= 1
        code, classes = _get(base, "/classes")
        entry = next(c for c in classes if c["class"] == recs[0]["class"])
        assert entry["batch_slots"] == 2
        code, health = _get(base, "/healthz")
        assert health["batch_slots"] == 2
    finally:
        d.scheduler.drain(timeout_s=30.0)
        d.close()


def test_daemon_batch_quantum_cut_bit_identity(tmp_path):
    """quantum=0 with a waiter cuts live slots at every boundary: jobs
    are checkpoint-cut out of the batch, requeued, and re-spliced — and
    every final result still lands the solo counts exactly (a cut of one
    slot never perturbs its neighbours)."""
    ref = _reference(N=10, M=256, K=4)
    d = ServeDaemon(port=0, state_dir=str(tmp_path / "state"),
                    batch_slots=2, quantum_s=0.0)
    _start_http_only(d)
    try:
        base = d.url
        ids = [_post(base, "/submit", NQ10K4)[1]["id"] for _ in range(3)]
        d.scheduler.start()
        recs = [_wait_final(base, jid) for jid in ids]
        for rec in recs:
            assert rec["state"] == "done", rec.get("error")
            assert _counts(rec) == (ref.explored_tree, ref.explored_sol,
                                    ref.best)
            assert rec["checkpoint"] is None  # consumed on completion
        assert sum(r["preemptions"] for r in recs) > 0
    finally:
        d.scheduler.drain(timeout_s=30.0)
        d.close()


def test_daemon_batch_cancel_one_slot_leaves_other(tmp_path, monkeypatch):
    """Cancelling one tenant mid-batch cuts exactly that slot (cancelled,
    with a resumable checkpoint and a partial result); its neighbour runs
    on to its budget bit-identically.

    TTS_PIPELINE=0 pins the solo reference to the synchronous dispatch
    sequence: a BUDGETED run's counts depend on how many dispatches
    actually execute, and solo speculative pipelining drains extra
    in-flight dispatches at the budget cut that the batched loop (which
    has no speculation) never issues.  Complete runs are invariant."""
    monkeypatch.setenv("TTS_PIPELINE", "0")
    ref = _reference(N=12, M=256, K=2, max_steps=30)
    d = ServeDaemon(port=0, state_dir=str(tmp_path / "state"),
                    batch_slots=2)
    _start_http_only(d)
    try:
        base = d.url
        spec = {"problem": "nqueens", "N": 12, "M": 256, "K": 2}
        _, s1 = _post(base, "/submit", {**spec, "max_steps": 30})
        _, s2 = _post(base, "/submit", {**spec, "max_steps": 1 << 20})
        d.scheduler.start()
        _wait_state(base, s2["id"], "running")
        code, _resp = _post(base, f"/job/{s2['id']}/cancel", {})
        assert code == 200
        rec2 = _wait_final(base, s2["id"])
        assert rec2["state"] == "cancelled"
        assert rec2["checkpoint"]  # cancel keeps the cut resumable
        assert rec2["result"]["complete"] is False
        rec1 = _wait_final(base, s1["id"])
        assert rec1["state"] == "done", rec1.get("error")
        assert rec1["steps"] == 30
        assert _counts(rec1) == (ref.explored_tree, ref.explored_sol,
                                 ref.best)
    finally:
        d.scheduler.drain(timeout_s=30.0)
        d.close()


def test_daemon_batch_budget_across_splices(tmp_path):
    """A max_steps budget is cumulative across batch splices: under
    quantum=0 churn the budgeted job is cut, requeued and re-spliced
    repeatedly, finishing 'done' only once the whole budget is spent."""
    d = ServeDaemon(port=0, state_dir=str(tmp_path / "state"),
                    batch_slots=2, quantum_s=0.0)
    _start_http_only(d)
    try:
        base = d.url
        _, sa = _post(base, "/submit", {**NQ10K4, "max_steps": 6})
        ids = [sa["id"]] + [_post(base, "/submit", NQ10K4)[1]["id"]
                            for _ in range(2)]
        d.scheduler.start()
        recs = [_wait_final(base, jid) for jid in ids]
        ref = _reference(N=10, M=256, K=4)
        assert recs[0]["state"] == "done", recs[0].get("error")
        assert recs[0]["steps"] == 6
        assert recs[0]["result"]["complete"] is False
        assert recs[0]["slices"] >= 2  # the budget spanned splices
        for rec in recs[1:]:
            assert rec["state"] == "done", rec.get("error")
            assert _counts(rec) == (ref.explored_tree, ref.explored_sol,
                                    ref.best)
    finally:
        d.scheduler.drain(timeout_s=30.0)
        d.close()


def test_daemon_batch_drain_requeues_live_slots(tmp_path):
    """Daemon drain with a full batch in flight: every live slot is cut
    to a checkpoint and requeued (resumable by the next daemon), never
    recorded as finished or lost."""
    d = ServeDaemon(port=0, state_dir=str(tmp_path / "state"),
                    batch_slots=2)
    _start_http_only(d)
    try:
        base = d.url
        spec = {"problem": "nqueens", "N": 13, "M": 256, "K": 8,
                "max_steps": 1 << 20}
        ids = [_post(base, "/submit", spec)[1]["id"] for _ in range(2)]
        d.scheduler.start()
        for jid in ids:
            _wait_state(base, jid, "running")
        d.scheduler.drain(timeout_s=60.0)
        for jid in ids:
            code, rec = _get(base, f"/job/{jid}")
            assert rec["state"] == "requeued", rec
            assert rec["checkpoint"]
            # The checkpoint endpoint serves the cut bytes for migration.
            req = urllib.request.urlopen(base + f"/job/{jid}/checkpoint",
                                         timeout=30)
            assert req.status == 200 and len(req.read()) > 0
    finally:
        d.close()


# -- cross-daemon migration (`tts migrate`) ----------------------------------


def test_migrate_checkpoint_bit_identity(tmp_path, capsys, monkeypatch):
    """`tts migrate`: a budgeted job cut on daemon A resumes on daemon B
    with the REMAINING budget, and the migrated final counts are
    bit-identical to one uninterrupted solo run of the whole budget —
    counters are cumulative across daemons via the portable checkpoint.

    TTS_PIPELINE=0 throughout (daemons AND reference): with speculation
    the drain cut banks in-flight dispatches beyond the recorded step
    count, so only the synchronous sequence splits exactly at a step
    boundary."""
    monkeypatch.setenv("TTS_PIPELINE", "0")
    from tpu_tree_search.serve.client import migrate_main

    spec = {"problem": "nqueens", "N": 12, "M": 256, "K": 64,
            "max_steps": 6}
    ref = _reference(N=12, M=256, K=64, max_steps=6)
    d1 = ServeDaemon(port=0, state_dir=str(tmp_path / "a"))
    d1.start()
    d2 = ServeDaemon(port=0, state_dir=str(tmp_path / "b"))
    d2.start()
    try:
        base1, base2 = d1.url, d2.url
        _, sub = _post(base1, "/submit", spec)
        jid = sub["id"]
        _wait_state(base1, jid, "running")
        # Deterministic mid-budget cut: drain requeues with a checkpoint.
        d1.scheduler.drain(timeout_s=60.0)
        code, rec = _get(base1, f"/job/{jid}")
        assert rec["state"] == "requeued" and rec["checkpoint"], rec
        s1 = rec["steps"]
        assert 1 <= s1 < 6
        port1 = int(base1.rsplit(":", 1)[1])
        assert migrate_main(jid, base2, port=port1) == 0
        out = capsys.readouterr().out
        assert jid in out and "steps_done" in out
        # Source side: consumed by the migration (cancelled, not lost).
        code, rec = _get(base1, f"/job/{jid}")
        assert rec["state"] == "cancelled"
        # Destination side: one job, resumed with the remaining budget.
        code, jobs2 = _get(base2, "/jobs")
        assert len(jobs2) == 1
        rec2 = _wait_final(base2, jobs2[0]["id"])
        assert rec2["state"] == "done", rec2.get("error")
        assert rec2["spec"]["max_steps"] == 6 - s1
        assert rec2["steps"] == 6 - s1
        assert _counts(rec2) == (ref.explored_tree, ref.explored_sol,
                                 ref.best)
        assert rec2["result"]["complete"] is False
    finally:
        d1.close()
        d2.scheduler.drain(timeout_s=30.0)
        d2.close()


def test_migrate_done_job_refused(tmp_path, capsys):
    """Migrating a finished job is a no-op with a clear message (rc 1),
    and a never-run cancelled job has no checkpoint to move (rc 2)."""
    from tpu_tree_search.serve.client import migrate_main

    d = ServeDaemon(port=0, state_dir=str(tmp_path / "state"))
    d.start()
    try:
        base = d.url
        port = int(base.rsplit(":", 1)[1])
        _, sub = _post(base, "/submit", NQ10K4)
        _wait_final(base, sub["id"])
        assert migrate_main(sub["id"], base, port=port) == 1
        assert migrate_main("job-999999", base, port=port) == 2
    finally:
        d.scheduler.drain(timeout_s=30.0)
        d.close()
