"""The GPU (Triton) kernel lowering and the TTS_KERNEL_BACKEND seam.

Correctness strategy (ops/backend.py): the GPU-flavored kernels — the
factored tile bodies rebuilt under Triton's constraints (no scratch refs,
no memory-space-pinned BlockSpecs, parallel CUDA-block grid) — run under
Pallas INTERPRET mode on this CPU suite, bit-compared against the same jnp
oracles the TPU kernels are gated on.  Interpret mode executes the kernel's
real index/math structure, so parity here proves the lowering computes the
same tree; `scripts/gpu_session.sh` stage 2 re-proves it compiled on a real
card.  The seam itself is contract-pinned (`kernel-backend-inert`,
`tts check`): off-GPU, every flavor but =gpu builds byte-identical programs.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pytest

from tpu_tree_search.engine.resident import resident_search
from tpu_tree_search.engine.sequential import sequential_search
from tpu_tree_search.ops import backend as BK
from tpu_tree_search.ops import nqueens_device, pallas_kernels, pfsp_device
from tpu_tree_search.problems import NQueensProblem, PFSPProblem
from tpu_tree_search.problems.pfsp import taillard


def _random_nodes(rng, jobs, B):
    prmu = np.stack([rng.permutation(jobs).astype(np.int32)
                     for _ in range(B)])
    limit1 = rng.integers(-1, jobs - 1, B).astype(np.int32)
    return jnp.asarray(prmu), jnp.asarray(limit1)


# -- knob resolution --------------------------------------------------------

def test_bad_knob_value_raises(monkeypatch):
    monkeypatch.setenv("TTS_KERNEL_BACKEND", "cuda")
    with pytest.raises(ValueError, match="TTS_KERNEL_BACKEND"):
        BK.kernel_backend_mode()


def test_resolution_table_on_cpu(monkeypatch):
    """The `_auto_compact`-style policy on a non-GPU process: auto -> jnp
    native; forced gpu -> non-native (interpret) but routes policy as gpu;
    forced tpu off-TPU keeps jnp routing (policy stays the raw platform)."""
    import jax

    if jax.default_backend() != "cpu":
        pytest.skip("resolution golden assumes the CPU suite backend")
    monkeypatch.delenv("TTS_KERNEL_BACKEND", raising=False)
    assert BK.resolve_backend() == BK.Backend("jnp", True)
    assert BK.kernel_kind() == "tpu" and BK.policy_backend() == "cpu"
    assert BK.profile_backend() == "cpu"
    monkeypatch.setenv("TTS_KERNEL_BACKEND", "gpu")
    assert BK.resolve_backend() == BK.Backend("gpu", False)
    assert BK.kernel_kind() == "gpu" and BK.policy_backend() == "gpu"
    assert BK.profile_backend() == "cpu+gpu"  # compound: never a chip row
    monkeypatch.setenv("TTS_KERNEL_BACKEND", "tpu")
    assert BK.resolve_backend() == BK.Backend("tpu", False)
    assert BK.kernel_kind() == "tpu" and BK.policy_backend() == "cpu"
    monkeypatch.setenv("TTS_KERNEL_BACKEND", "jnp")
    assert pallas_kernels.use_pallas() is False


# -- kernel-level interpret bit-parity (the CI half of the GPU story) -------

@pytest.mark.parametrize("bf16", [False, True])
@pytest.mark.parametrize("inst,jobs,machines", [(14, 20, 10), (1, 12, 5)])
def test_lb1_gpu_matches_oracle(inst, jobs, machines, bf16):
    rng = np.random.default_rng(3)
    if jobs == 20:
        prob = PFSPProblem(inst=inst, lb="lb1", ub=1)
    else:
        ptm = taillard.reduced_instance(inst, jobs=jobs, machines=machines)
        prob = PFSPProblem(lb="lb1", ub=0, p_times=ptm)
    t = pfsp_device.PFSPDeviceTables(prob.lb1_data, prob.lb2_data)
    pd, ld = _random_nodes(rng, jobs, 300)
    oracle = pfsp_device._lb1_chunk(pd, ld, t.ptm_t, t.min_heads, t.min_tails)
    got = pallas_kernels.pfsp_lb1_bounds(
        pd, ld, t.ptm_t, t.min_heads, t.min_tails,
        interpret=True, bf16=bf16, backend="gpu",
    )
    assert np.array_equal(np.asarray(oracle), np.asarray(got))


@pytest.mark.parametrize("inst,jobs,machines", [(14, 20, 10), (1, 12, 5)])
def test_lb1_d_gpu_matches_oracle(inst, jobs, machines):
    rng = np.random.default_rng(5)
    if jobs == 20:
        prob = PFSPProblem(inst=inst, lb="lb1_d", ub=1)
    else:
        ptm = taillard.reduced_instance(inst, jobs=jobs, machines=machines)
        prob = PFSPProblem(lb="lb1_d", ub=0, p_times=ptm)
    t = pfsp_device.PFSPDeviceTables(prob.lb1_data, prob.lb2_data)
    pd, ld = _random_nodes(rng, jobs, 300)
    oracle = pfsp_device._lb1_d_chunk(pd, ld, t.ptm_t, t.min_heads,
                                      t.min_tails)
    got = pallas_kernels.pfsp_lb1_d_bounds(
        pd, ld, t.ptm_t, t.min_heads, t.min_tails,
        interpret=True, backend="gpu",
    )
    assert np.array_equal(np.asarray(oracle), np.asarray(got))


@pytest.mark.parametrize("pair_group", [1, 4, None])
@pytest.mark.parametrize("inst", [14, 21])
def test_lb2_gpu_matches_oracle(inst, pair_group):
    """lb2 under the gpu flavor across the pair-group unroll axis, on
    ta014 (P=45) and ta021 (20x20, P=190 — where the auto policy
    genuinely blocks).  Open child slots only: closed slots are garbage
    by contract."""
    rng = np.random.default_rng(7 + inst)
    prob = PFSPProblem(inst=inst, lb="lb2", ub=1)
    jobs = prob.jobs
    t = pfsp_device.PFSPDeviceTables(prob.lb1_data, prob.lb2_data)
    pd, ld = _random_nodes(rng, jobs, 200)
    oracle = pfsp_device._lb2_chunk(
        pd, ld, t.ptm_t, t.min_heads, t.min_tails,
        t.pairs, t.lags, t.johnson_schedules,
    )
    got = pallas_kernels.pfsp_lb2_bounds(
        pd, ld, t, interpret=True, pair_group=pair_group, backend="gpu"
    )
    open_ = np.arange(jobs)[None, :] >= np.asarray(ld)[:, None] + 1
    assert np.array_equal(np.asarray(oracle)[open_], np.asarray(got)[open_])


def test_lb2_self_gpu_matches_chunk_with_gating():
    rng = np.random.default_rng(23)
    prob = PFSPProblem(inst=14, lb="lb2", ub=1)
    t = pfsp_device.PFSPDeviceTables(prob.lb1_data, prob.lb2_data)
    R = 600  # not a tile multiple: exercises padding
    pd, ld = _random_nodes(rng, prob.jobs, R)
    oracle = np.asarray(pfsp_device._lb2_self_chunk(
        pd, ld, t.ptm_t, t.min_heads, t.min_tails,
        t.pairs, t.lags, t.johnson_schedules,
    ))
    for n_active in (R, 97):
        got = np.asarray(pallas_kernels.pfsp_lb2_self_bounds(
            pd, ld, n_active, t, interpret=True, backend="gpu",
        ))
        assert np.array_equal(got[:n_active], oracle[:n_active])


@pytest.mark.parametrize("g", [1, 3])
@pytest.mark.parametrize("N", [9, 12])
def test_nqueens_gpu_matches_oracle(N, g):
    rng = np.random.default_rng(7)
    B = 700  # not a tile multiple: exercises padding
    boards = np.stack([rng.permutation(N).astype(np.uint8)
                       for _ in range(B)])
    depth = rng.integers(0, N + 1, B).astype(np.int32)
    oracle = nqueens_device.make_core(N, g)(jnp.asarray(boards),
                                            jnp.asarray(depth))
    got = pallas_kernels.nqueens_labels(
        jnp.asarray(boards), jnp.asarray(depth), N, g,
        interpret=True, backend="gpu",
    )
    assert np.array_equal(np.asarray(oracle), np.asarray(got))


# -- engine-level fuzz: forced-gpu searches land the sequential counts ------

def _reduced_problem(lb: str):
    ptm = taillard.reduced_instance(14, jobs=10, machines=5)
    return PFSPProblem(lb=lb, ub=0, p_times=ptm)


@pytest.mark.parametrize("compact", ["auto", "dense", "scatter"])
@pytest.mark.parametrize("narrow", ["0", "auto"])
def test_resident_gpu_lb1_matches_sequential(compact, narrow, monkeypatch):
    """Full resident searches with the gpu flavor forced end to end:
    TTS_KERNEL_BACKEND=gpu routes the policy tables through the gpu rows
    (`policy_backend`) and — with TTS_PALLAS=force re-arming the demoted
    lb1 family — runs the GPU-lowered kernels interpreted inside the real
    engine, across the compact-mode and narrow-storage axes.  Counts must
    land exactly on the sequential tier's."""
    monkeypatch.setenv("TTS_KERNEL_BACKEND", "gpu")
    monkeypatch.setenv("TTS_PALLAS", "force")
    monkeypatch.setenv("TTS_COMPACT", compact)
    monkeypatch.setenv("TTS_NARROW", narrow)
    opt = sequential_search(_reduced_problem("lb1")).best
    seq = sequential_search(_reduced_problem("lb1"), initial_best=opt)
    res = resident_search(_reduced_problem("lb1"), m=4, M=64, K=8,
                          initial_best=opt)
    assert (res.explored_tree, res.explored_sol) == (
        seq.explored_tree, seq.explored_sol
    )
    assert res.best == opt
    assert res.kernel_backend == "gpu"


@pytest.mark.parametrize("pairblock", ["1", "auto"])
def test_resident_gpu_lb2_matches_sequential(pairblock, monkeypatch):
    monkeypatch.setenv("TTS_KERNEL_BACKEND", "gpu")
    monkeypatch.setenv("TTS_LB2_PAIRBLOCK", pairblock)
    opt = sequential_search(_reduced_problem("lb2")).best
    seq = sequential_search(_reduced_problem("lb2"), initial_best=opt)
    res = resident_search(_reduced_problem("lb2"), m=4, M=64, K=8,
                          initial_best=opt)
    assert (res.explored_tree, res.explored_sol) == (
        seq.explored_tree, seq.explored_sol
    )
    assert res.best == opt


def test_resident_gpu_nqueens_matches_sequential(monkeypatch):
    monkeypatch.setenv("TTS_KERNEL_BACKEND", "gpu")
    monkeypatch.setenv("TTS_PALLAS", "force")
    seq = sequential_search(NQueensProblem(N=9))
    res = resident_search(NQueensProblem(N=9), m=4, M=64, K=8)
    assert (res.explored_tree, res.explored_sol) == (
        seq.explored_tree, seq.explored_sol
    )


# -- the cache seam: a knob flip rebuilds, a flip back hits -----------------

def test_knob_flip_rebuilds_program_and_flip_back_hits(monkeypatch):
    """The raw knob + resolved kind ride routing_cache_token, so =gpu must
    build a DISTINCT resident program from the unset build, and restoring
    the knob must hit the original cached program (same object — the
    token round-trips)."""
    import jax

    from tpu_tree_search.engine.resident import _make_program, resolve_capacity

    prob = _reduced_problem("lb1")
    monkeypatch.delenv("TTS_KERNEL_BACKEND", raising=False)
    tok0 = pfsp_device.routing_cache_token(prob)
    capacity, M = resolve_capacity(prob, 64, None)
    dev = jax.devices()[0]
    p0 = _make_program(prob, 4, M, 8, capacity, dev)
    monkeypatch.setenv("TTS_KERNEL_BACKEND", "gpu")
    assert pfsp_device.routing_cache_token(prob) != tok0
    p_gpu = _make_program(prob, 4, M, 8, capacity, dev)
    assert p_gpu is not p0
    monkeypatch.delenv("TTS_KERNEL_BACKEND", raising=False)
    assert pfsp_device.routing_cache_token(prob) == tok0
    assert _make_program(prob, 4, M, 8, capacity, dev) is p0


# -- reporting: the banner and --json carry the resolved flavor -------------

def test_cli_json_records_backend_and_refusal(capsys, monkeypatch):
    """Under the forced gpu flavor on a non-GPU host the --json record
    must carry kernel_backend + kernel_backend_mode, and the megakernel
    resolver's refusal must name the real reason (gpu kernels are not
    native here), not the generic not-on-TPU line."""
    import json

    from tpu_tree_search import cli

    monkeypatch.setenv("TTS_KERNEL_BACKEND", "gpu")
    assert cli.main(["nqueens", "--N", "6", "--tier", "device",
                     "--engine", "resident", "--m", "4", "--M", "64",
                     "--json"]) == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["kernel_backend"] == "gpu"
    assert rec["kernel_backend_mode"] == "gpu"
    assert "not native here" in rec["megakernel_reason"]


def test_cli_banner_names_forced_backend(capsys, monkeypatch):
    monkeypatch.setenv("TTS_KERNEL_BACKEND", "gpu")
    from tpu_tree_search import cli

    assert cli.main(["nqueens", "--N", "6", "--tier", "device",
                     "--engine", "resident", "--m", "4", "--M", "64"]) == 0
    out = capsys.readouterr().out
    assert "Kernel backend: gpu (forced: gpu)" in out


def test_cli_json_default_backend_unforced(capsys, monkeypatch):
    """Unset knob: the record reports the auto-resolved flavor and omits
    kernel_backend_mode (no forced spelling to preserve)."""
    import json

    from tpu_tree_search import cli

    monkeypatch.delenv("TTS_KERNEL_BACKEND", raising=False)
    assert cli.main(["nqueens", "--N", "6", "--tier", "device",
                     "--engine", "resident", "--m", "4", "--M", "64",
                     "--json"]) == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["kernel_backend"] == "tpu"  # the flavor of record off-GPU
    assert "kernel_backend_mode" not in rec
