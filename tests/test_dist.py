"""Distributed-tier tests with virtual hosts (threads) on the 8-device CPU
platform — the fake multi-host runtime of SURVEY.md §4 implication (d)."""

import pytest

from tpu_tree_search.engine import sequential_search
from tpu_tree_search.parallel.dist import ThreadCollectives, dist_search
from tpu_tree_search.problems import NQueensProblem, PFSPProblem
from tpu_tree_search.problems.pfsp import taillard as T


def test_thread_collectives():
    import threading

    coll = ThreadCollectives(3)
    out = {}

    def run(h):
        c = coll.bind(h)
        out[h] = (c.allreduce_sum(h + 1), c.allreduce_min(h), c.allreduce_max(h))

    ts = [threading.Thread(target=run, args=(h,)) for h in range(3)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert out[0] == (6, 0, 2)
    assert out[0] == out[1] == out[2]


@pytest.mark.parametrize("H,D", [(2, 2), (4, 1)])
def test_nqueens_dist_matches_sequential(H, D):
    seq = sequential_search(NQueensProblem(N=9))
    ds = dist_search(NQueensProblem(N=9), m=5, M=128, D=D, num_hosts=H)
    assert ds.explored_sol == seq.explored_sol
    assert ds.explored_tree == seq.explored_tree


@pytest.mark.parametrize("lb", ["lb1", "lb2"])
def test_pfsp_dist_finds_optimum(lb):
    ptm = T.reduced_instance(14, jobs=7, machines=5)
    seq = sequential_search(PFSPProblem(lb=lb, ub=0, p_times=ptm))
    ds = dist_search(
        PFSPProblem(lb=lb, ub=0, p_times=ptm), m=5, M=64, D=2, num_hosts=2
    )
    assert ds.best == seq.best


def test_pfsp_dist_fixed_incumbent_parity():
    ptm = T.reduced_instance(14, jobs=8, machines=5)
    opt = sequential_search(PFSPProblem(lb="lb1", ub=0, p_times=ptm)).best
    seq = sequential_search(PFSPProblem(lb="lb1", ub=0, p_times=ptm), initial_best=opt)
    ds = dist_search(
        PFSPProblem(lb="lb1", ub=0, p_times=ptm),
        m=5, M=64, D=2, num_hosts=2, initial_best=opt,
    )
    assert ds.best == opt
    assert ds.explored_tree == seq.explored_tree
    assert ds.explored_sol == seq.explored_sol


def test_dist_single_host_degenerate():
    seq = sequential_search(NQueensProblem(N=8))
    ds = dist_search(NQueensProblem(N=8), m=5, M=128, num_hosts=1)
    assert ds.explored_sol == seq.explored_sol
    assert ds.explored_tree == seq.explored_tree


def test_allgather_obj_threads():
    import threading

    coll = ThreadCollectives(3)
    out = {}

    def run(h):
        c = coll.bind(h)
        out[h] = c.allgather_obj({"h": h, "payload": list(range(h))})

    ts = [threading.Thread(target=run, args=(h,)) for h in range(3)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert out[0] == out[2]
    assert [r["h"] for r in out[1]] == [0, 1, 2]


def test_skewed_partition_inter_host_steal():
    """One virtual host starts with ZERO warm nodes; host-mediated stealing
    must feed it real step-2 work (not just drain leftovers), and the totals
    must still match the sequential goldens exactly (N-Queens never prunes,
    so stealing can only change visit order). Reference behavior matched:
    `pfsp_dist_multigpu_chpl.chpl:520-567`."""

    def all_to_host0(warm, host_id, num_hosts):
        if host_id == 0:
            return warm
        return {k: v[:0] for k, v in warm.items()}

    H, D = 2, 2
    seq = sequential_search(NQueensProblem(N=10))
    ds = dist_search(
        NQueensProblem(N=10), m=5, M=64, D=D, num_hosts=H,
        steal_interval_s=0.005, partition_fn=all_to_host0,
    )
    assert ds.explored_tree == seq.explored_tree
    assert ds.explored_sol == seq.explored_sol
    host1_tree = sum(ds.per_worker_tree[D:])
    assert host1_tree > 0, "starved host explored nothing — no steal happened"


def test_dist_steal_disabled_mpi_baseline_semantics():
    """steal=False keeps the MPI baseline's join-point-only communication
    (`pfsp_dist_multigpu_cuda.c:570-623`) and stays exact."""
    seq = sequential_search(NQueensProblem(N=9))
    ds = dist_search(
        NQueensProblem(N=9), m=5, M=128, D=2, num_hosts=2, steal=False
    )
    assert ds.explored_tree == seq.explored_tree
    assert ds.explored_sol == seq.explored_sol


def test_pfsp_dist_steal_improving_incumbent():
    """ub=0 with stealing + periodic UB exchange must still find the
    optimum (B&B relaxation: node counts may differ, optimum may not)."""
    ptm = T.reduced_instance(21, jobs=8, machines=6)
    seq = sequential_search(PFSPProblem(lb="lb2", ub=0, p_times=ptm))
    ds = dist_search(
        PFSPProblem(lb="lb2", ub=0, p_times=ptm),
        m=5, M=64, D=2, num_hosts=2, steal_interval_s=0.005,
    )
    assert ds.best == seq.best


def test_dist_terminates_with_drain_leftovers():
    """Regression: with m=25 and D=3 the per-pool drain leftovers (< m each)
    can sum past 2m per host while NO single pool can donate — the
    quiescence test must key on the largest pool, or termination never
    fires and the tier hangs."""
    seq = sequential_search(NQueensProblem(N=9))
    ds = dist_search(
        NQueensProblem(N=9), m=25, M=64, D=3, num_hosts=2,
        steal_interval_s=0.005,
    )
    assert ds.explored_tree == seq.explored_tree
    assert ds.explored_sol == seq.explored_sol


def test_thread_collectives_kv_channel():
    """kv_set/kv_get: the point-to-point donation channel (payloads never
    broadcast to non-receivers)."""
    import threading

    coll = ThreadCollectives(2)
    got = {}

    def sender():
        coll.bind(0)
        coll.kv_set("tts/steal/1/0->1", b"payload-bytes")

    def receiver():
        coll.bind(1)
        got["v"] = coll.kv_get("tts/steal/1/0->1", timeout_s=5.0)

    ts = [threading.Thread(target=receiver), threading.Thread(target=sender)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert got["v"] == b"payload-bytes"
    assert coll._kv == {}  # consumed, nothing left behind


def test_thread_collectives_kv_get_timeout():
    coll = ThreadCollectives(1)
    coll.bind(0)
    with pytest.raises(TimeoutError):
        coll.kv_get("missing", timeout_s=0.1)


def test_pop_front_bulk_half_cap():
    """Donation blocks are capped so a huge pool never ships an unbounded
    payload (VERDICT r3 weak #1; the mesh tier's bounded-donation policy)."""
    import numpy as np

    from tpu_tree_search.pool import SoAPool

    p = SoAPool({"x": ((), np.int32)})
    p.push_back_bulk({"x": np.arange(10000, dtype=np.int32)})
    batch = p.pop_front_bulk_half(m=5, perc=0.5, cap=64)
    assert batch["x"].shape[0] == 64
    assert list(batch["x"][:3]) == [0, 1, 2]  # still from the front
    assert p.size == 10000 - 64
    # uncapped keeps the steal-half policy
    batch2 = p.pop_front_bulk_half(m=5, perc=0.5)
    assert batch2["x"].shape[0] == (10000 - 64) // 2


def test_skewed_partition_donations_bounded():
    """Integration: with one starved host and a tiny M, every delivered
    donation block respects the M cap (sum over blocks <= blocks * M)."""

    def all_to_host0(warm, host_id, num_hosts):
        if host_id == 0:
            return warm
        return {k: v[:0] for k, v in warm.items()}

    M = 32
    ds = dist_search(
        NQueensProblem(N=10), m=5, M=M, D=2, num_hosts=2,
        steal_interval_s=0.005, partition_fn=all_to_host0,
    )
    seq = sequential_search(NQueensProblem(N=10))
    assert ds.explored_tree == seq.explored_tree
    assert ds.comm is not None and ds.comm["blocks_received"] > 0
    assert ds.comm["nodes_received"] <= ds.comm["blocks_received"] * M
    assert ds.comm["nodes_sent"] == ds.comm["nodes_received"]


def test_balanced_run_cadence_backs_off():
    """When no host is needy the exchange cadence backs off geometrically
    (VERDICT r3 weak #4): a balanced run must do far fewer collective rounds
    than the fixed-interval cadence would."""
    interval = 0.002
    ds = dist_search(
        NQueensProblem(N=10), m=5, M=2048, D=2, num_hosts=2,
        steal_interval_s=interval,
    )
    seq = sequential_search(NQueensProblem(N=10))
    assert ds.explored_tree == seq.explored_tree
    fixed_cadence_rounds = ds.elapsed / interval
    assert ds.comm["rounds"] < max(10.0, fixed_cadence_rounds / 2), (
        ds.comm,
        ds.elapsed,
    )


def test_comm_transport_failure_requeues_inflight_donation():
    """A donation popped from a local pool but never delivered (the
    transport dies inside kv_set) must be REQUEUED, not lost — the
    `_inflight` path (VERDICT r4 #8). The reference has no analogue: a
    crashed locale loses its in-flight steal and hangs allIdle forever
    (SURVEY.md §5)."""
    import threading

    import numpy as np

    from tpu_tree_search.parallel.dist import _HostComm
    from tpu_tree_search.pool import ParallelSoAPool

    m = 5

    class _DyingTransport:
        """Round 1: host 0 (rich, busy) is matched to donate to host 1
        (idle, starving); the KV send then dies."""

        num_hosts = 2
        host_id = 0

        def allgather_obj(self, row):
            return [row, (0, 0, row[2], True, False, None)]

        def kv_set(self, key, value):
            raise RuntimeError("transport died mid-donation")

        def kv_get(self, key, timeout_s):
            raise AssertionError("host 0 never receives")

    class _States:
        flag = threading.Event()

        def _all_idle(self):
            return False

    class _Shared:
        def read(self):
            return 10**9

        def publish(self, v):
            return v

    pool = ParallelSoAPool({"x": ((), np.int32)})
    pool.push_back_bulk({"x": np.arange(100, dtype=np.int32)})
    comm = _HostComm(_DyingTransport(), m, interval_s=0.0)
    stop = threading.Event()
    comm.run([pool], _States(), _Shared(), stop)

    assert isinstance(comm.error, RuntimeError), comm.error
    assert "mid-donation" in str(comm.error)
    assert stop.is_set()  # workers unblock instead of polling forever
    assert comm._inflight is None
    assert pool.size == 100  # the popped block went back — zero node loss


def test_dist_worker_death_aborts_cleanly_with_root_cause():
    """A worker dying mid-search (evaluator raises) under the full
    2-virtual-host dist tier with steal churn: every host must stop
    promptly and dist_search must surface the WORKER's error — not a
    secondary BrokenBarrierError / kv timeout from a peer that was mid-
    collective when the abort hit. The reference instead hangs allIdle
    forever on a crashed task (SURVEY.md §5)."""
    calls = {"n": 0}
    orig = NQueensProblem.generate_children

    def dying(self, snapshot, count, results, best):
        calls["n"] += 1
        if calls["n"] > 3:  # let some real chunks/steals happen first
            raise RuntimeError("injected worker death")
        return orig(self, snapshot, count, results, best)

    def skew(warm, host_id, num_hosts):
        # Everything on host 0: host 1 only works via donation churn.
        return {k: (v if host_id == 0 else v[:0]) for k, v in warm.items()}

    import time as _time

    from unittest import mock

    t0 = _time.monotonic()
    with mock.patch.object(NQueensProblem, "generate_children", dying):
        with pytest.raises(RuntimeError, match="injected worker death"):
            dist_search(
                NQueensProblem(N=10), m=5, M=64, D=2, num_hosts=2,
                steal_interval_s=0.005, partition_fn=skew,
            )
    assert _time.monotonic() - t0 < 60.0  # clean abort, not a hang


def _free_port() -> int:
    """Ephemeral port for a jax.distributed coordinator: bind to 0, let the
    OS pick, release. (Races are possible but vanishingly rarer than a fixed
    constant colliding with a concurrent run or a leftover listener.)"""
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_jax_collectives_single_process_subprocess():
    """JaxCollectives (the real-pod DCN backend) exercised end to end in a
    1-process jax.distributed universe — run in a subprocess because
    jax.distributed.initialize is once-per-process and would leak into the
    rest of the suite."""
    import subprocess
    import sys

    code = """
import os
os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize("localhost:@PORT@", num_processes=1,
                           process_id=0)
from tpu_tree_search.parallel.dist import JaxCollectives, dist_search
from tpu_tree_search.problems import NQueensProblem
from tpu_tree_search.engine.sequential import sequential_search

coll = JaxCollectives()
assert coll.num_hosts == 1 and coll.host_id == 0
assert coll.allreduce_sum(7) == 7
assert coll.allreduce_min(3.5) == 3.5
got = coll.allgather_obj({"blob": list(range(5))})
assert got == [{"blob": [0, 1, 2, 3, 4]}]
coll.kv_set("tts/steal/7/0->0", b"kv-bytes")
assert coll.kv_get("tts/steal/7/0->0", timeout_s=5.0) == b"kv-bytes"

seq = sequential_search(NQueensProblem(N=8))
res = dist_search(NQueensProblem(N=8), m=5, M=64)
assert res.explored_sol == seq.explored_sol
assert res.explored_tree == seq.explored_tree
print("JAX_COLLECTIVES_OK")
""".replace("@PORT@", str(_free_port()))
    res = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=240,
    )
    assert "JAX_COLLECTIVES_OK" in res.stdout, res.stderr[-2000:]


_TWO_PROC_WORKER = """
import os, sys
rank = int(sys.argv[1]); port = sys.argv[2]
os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(f"localhost:{port}", num_processes=2,
                           process_id=rank)
from tpu_tree_search.parallel.dist import JaxCollectives, dist_search
from tpu_tree_search.problems import NQueensProblem

coll = JaxCollectives()
assert coll.num_hosts == 2 and coll.host_id == rank

# Reductions see both ranks' contributions.
assert coll.allreduce_sum(10 + rank) == 21
assert coll.allreduce_min(float(rank)) == 0.0
assert coll.allreduce_max(float(rank)) == 1.0

# Object allgather with rank-asymmetric payload sizes (pads to max length).
got = coll.allgather_obj({"rank": rank, "pad": "x" * (100 * (rank + 1))})
assert [g["rank"] for g in got] == [0, 1]
assert len(got[1]["pad"]) == 200

# KV store: real cross-process point-to-point both ways.
coll.kv_set(f"tts/test/{rank}", bytes([rank]) * 64)
peer = coll.kv_get(f"tts/test/{1 - rank}", timeout_s=30.0)
assert peer == bytes([1 - rank]) * 64

# End-to-end distributed search with the inter-host communicator on, under
# a skewed partition (everything to host 0) so host 1 can only contribute
# via a real DCN donation round.
def skew(warm, host_id, num_hosts):
    return {k: (v if host_id == 0 else v[:0]) for k, v in warm.items()}

res = dist_search(NQueensProblem(N=10), m=5, M=256, D=2,
                  steal_interval_s=0.005, partition_fn=skew)
assert res.explored_tree == 35538, res.explored_tree
assert res.explored_sol == 724, res.explored_sol
assert res.comm is not None and res.comm["rounds"] > 0

# Checkpoint/resume through the real coordination service: the comm-round
# cut + two-phase commit (allgather of staging OKs, atomic rename) runs
# over actual jax.distributed collectives; resume must hit the goldens.
import os, tempfile
ckpt = os.path.join(tempfile.gettempdir(), f"tts_2proc_{port}.ckpt")
for stale in (f"{ckpt}.h{rank}", f"{ckpt}.h{rank}.staging"):
    # A prior run's files must not green-light a broken checkpoint path.
    if os.path.exists(stale):
        os.remove(stale)
# interval 0.0: the cut fires on the second comm round, guaranteeing a
# file before quiescence (which itself needs two further idle rounds).
res2 = dist_search(NQueensProblem(N=10), m=5, M=256, D=2,
                   steal_interval_s=0.005, checkpoint_path=ckpt,
                   checkpoint_interval_s=0.0)
assert res2.explored_tree == 35538
assert os.path.exists(f"{ckpt}.h{rank}"), "per-host cut missing"
res3 = dist_search(NQueensProblem(N=10), m=5, M=256, D=2,
                   steal_interval_s=0.005, resume_from=ckpt)
assert res3.explored_tree == 35538 and res3.explored_sol == 724

# dist_mesh over the SAME real coordination service: per-process mesh
# engines, allgather exchange + KV donations across actual processes.
from tpu_tree_search.parallel.dist_mesh import dist_mesh_search
res4 = dist_mesh_search(NQueensProblem(N=10), m=5, M=128, K=4, D=2,
                        partition_fn=skew)
assert res4.explored_tree == 35538, res4.explored_tree
assert res4.explored_sol == 724, res4.explored_sol
assert res4.comm is not None and res4.comm["blocks_received"] > 0
print(f"RANK{rank}_OK donations={res.comm['blocks_received']}")
"""


_FOUR_PROC_WORKER = """
import os, sys
rank = int(sys.argv[1]); port = sys.argv[2]
os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(f"localhost:{port}", num_processes=4,
                           process_id=rank)
from tpu_tree_search.parallel.dist import dist_search
from tpu_tree_search.problems import NQueensProblem

# Every warm-up node lands on host 0: hosts 1-3 contribute ONLY through
# repeated coordination-service donation rounds (steal churn at 4-host
# scale; the donor re-matches each round as receivers drain).
def skew(warm, host_id, num_hosts):
    return {k: (v if host_id == 0 else v[:0]) for k, v in warm.items()}

res = dist_search(NQueensProblem(N=10), m=5, M=128, D=1,
                  steal_interval_s=0.005, partition_fn=skew)
assert res.explored_tree == 35538, res.explored_tree
assert res.explored_sol == 724, res.explored_sol
assert res.comm is not None and res.comm["blocks_received"] > 0
print(f"RANK{rank}_OK donations={res.comm['blocks_received']}")
"""


def test_jax_collectives_four_processes_steal_churn():
    """Four REAL jax.distributed processes with a fully skewed partition:
    three starving hosts drain host 0 through repeated donation rounds
    (VERDICT r4 #8's scale-up of the 2-process test). Parity against the
    N=10 goldens proves no node was lost or double-explored across the
    churn."""
    import subprocess
    import sys

    port = _free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _FOUR_PROC_WORKER, str(rank), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for rank in range(4)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=300)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for rank, (rc, out, err) in enumerate(outs):
        assert rc == 0 and f"RANK{rank}_OK" in out, (
            f"rank {rank}: rc={rc}\nstdout: {out[-1000:]}\nstderr: {err[-2000:]}"
        )


_KILLED_PEER_WORKER = """
import os, sys, time
rank = int(sys.argv[1]); port = sys.argv[2]
os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
# Short heartbeat so the coordination service detects the dead peer in
# seconds, not the 100s default — the knob a real pod deployment would set.
# (Older jax lacks the kwarg; its ~100s default detection window still
# sits inside this test's 120s fail-stop bound.)
import inspect
_hb = ({"heartbeat_timeout_seconds": 10}
       if "heartbeat_timeout_seconds"
       in inspect.signature(jax.distributed.initialize).parameters else {})
jax.distributed.initialize(f"localhost:{port}", num_processes=2,
                           process_id=rank, **_hb)
from tpu_tree_search.parallel.dist import JaxCollectives, dist_search
from tpu_tree_search.problems import NQueensProblem

if rank == 1:
    # Die mid-donation: after the matching allgather picked this host as
    # the receiver, while the donor's payload sits undelivered in the KV
    # store. SIGKILL — no atexit, no distributed shutdown, a real crash.
    real_get = JaxCollectives.kv_get
    def dying_get(self, key, timeout_s):
        if "/steal/" in key:
            os.kill(os.getpid(), 9)
        return real_get(self, key, timeout_s)
    JaxCollectives.kv_get = dying_get

def skew(warm, host_id, num_hosts):
    # All work on host 0: host 1 only lives off donations, so a donation
    # round (and the kill) happens immediately and repeatedly.
    return {k: (v if host_id == 0 else v[:0]) for k, v in warm.items()}

t0 = time.monotonic()
try:
    dist_search(NQueensProblem(N=12), m=5, M=256, D=1,
                steal_interval_s=0.005, partition_fn=skew)
except BaseException as e:
    dt = time.monotonic() - t0
    print(f"SURVIVOR_ABORTED after {dt:.1f}s: {type(e).__name__}: {e}",
          flush=True)
    # os._exit: jax's atexit shutdown barrier necessarily LOG(FATAL)s once
    # the peer is dead; the property under test — the SEARCH fail-stopped
    # with a root cause — has already been decided above.
    os._exit(0 if dt < 120.0 else 3)
print("UNEXPECTED_COMPLETION", flush=True)
os._exit(4)
"""


def test_jax_collectives_killed_peer_fail_stop():
    """One of two REAL jax.distributed processes is SIGKILLed mid-donation
    (matched as receiver, payload undelivered). The survivor must fail-stop
    — surface an error from the collective/KV layer within the heartbeat
    window and unblock its workers — not hang. The Chapel reference hangs
    allIdle forever on a crashed locale (SURVEY.md §5); MPI aborts the
    whole job with no diagnostic. Completes VERDICT r4 #8."""
    import subprocess
    import sys

    port = _free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _KILLED_PEER_WORKER, str(rank), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for rank in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=240)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    rc0, out0, err0 = outs[0]
    rc1, out1, _ = outs[1]
    # Rank 1 died by SIGKILL (negative return code), printing nothing.
    assert rc1 != 0 and "SURVIVOR" not in out1, (rc1, out1[-500:])
    # Rank 0 noticed and fail-stopped in bounded time with a root cause.
    # Two jax behaviors qualify: current jax surfaces the dead peer as an
    # exception from the collective/KV layer (graceful SURVIVOR_ABORTED);
    # older jax's coordination client LOG(FATAL)s the surviving process
    # the moment error polling reports the unhealthy peer — a hard abort,
    # but still a bounded fail-stop naming the dead task on stderr (vs the
    # reference, which hangs allIdle forever). Either way: no hang, cause
    # surfaced.
    graceful = rc0 == 0 and "SURVIVOR_ABORTED" in out0
    hard_abort = rc0 != 0 and (
        "stopped sending heartbeats" in err0
        or "distributed service detected fatal errors" in err0
    )
    assert graceful or hard_abort, (
        f"rc={rc0}\nstdout: {out0[-1000:]}\nstderr: {err0[-2000:]}"
    )


def test_jax_collectives_two_processes():
    """Two REAL jax.distributed processes (CPU backend, 2 virtual devices
    each) through JaxCollectives end to end: reductions, asymmetric-size
    allgather_obj, cross-process KV delivery, and a dist_search whose
    partition sends every warm-up node to host 0 — host 1 participates only
    through actual coordination-service donation traffic (VERDICT r3 #6)."""
    import subprocess
    import sys

    port = _free_port()  # a fixed port collides with concurrent CI runs
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _TWO_PROC_WORKER, str(rank), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for rank in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=300)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for rank, (rc, out, err) in enumerate(outs):
        assert rc == 0 and f"RANK{rank}_OK" in out, (
            f"rank {rank}: rc={rc}\nstdout: {out[-1000:]}\nstderr: {err[-2000:]}"
        )
