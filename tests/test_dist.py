"""Distributed-tier tests with virtual hosts (threads) on the 8-device CPU
platform — the fake multi-host runtime of SURVEY.md §4 implication (d)."""

import pytest

from tpu_tree_search.engine import sequential_search
from tpu_tree_search.parallel.dist import ThreadCollectives, dist_search
from tpu_tree_search.problems import NQueensProblem, PFSPProblem
from tpu_tree_search.problems.pfsp import taillard as T


def test_thread_collectives():
    import threading

    coll = ThreadCollectives(3)
    out = {}

    def run(h):
        c = coll.bind(h)
        out[h] = (c.allreduce_sum(h + 1), c.allreduce_min(h), c.allreduce_max(h))

    ts = [threading.Thread(target=run, args=(h,)) for h in range(3)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert out[0] == (6, 0, 2)
    assert out[0] == out[1] == out[2]


@pytest.mark.parametrize("H,D", [(2, 2), (4, 1)])
def test_nqueens_dist_matches_sequential(H, D):
    seq = sequential_search(NQueensProblem(N=9))
    ds = dist_search(NQueensProblem(N=9), m=5, M=128, D=D, num_hosts=H)
    assert ds.explored_sol == seq.explored_sol
    assert ds.explored_tree == seq.explored_tree


@pytest.mark.parametrize("lb", ["lb1", "lb2"])
def test_pfsp_dist_finds_optimum(lb):
    ptm = T.reduced_instance(14, jobs=7, machines=5)
    seq = sequential_search(PFSPProblem(lb=lb, ub=0, p_times=ptm))
    ds = dist_search(
        PFSPProblem(lb=lb, ub=0, p_times=ptm), m=5, M=64, D=2, num_hosts=2
    )
    assert ds.best == seq.best


def test_pfsp_dist_fixed_incumbent_parity():
    ptm = T.reduced_instance(14, jobs=8, machines=5)
    opt = sequential_search(PFSPProblem(lb="lb1", ub=0, p_times=ptm)).best
    seq = sequential_search(PFSPProblem(lb="lb1", ub=0, p_times=ptm), initial_best=opt)
    ds = dist_search(
        PFSPProblem(lb="lb1", ub=0, p_times=ptm),
        m=5, M=64, D=2, num_hosts=2, initial_best=opt,
    )
    assert ds.best == opt
    assert ds.explored_tree == seq.explored_tree
    assert ds.explored_sol == seq.explored_sol


def test_dist_single_host_degenerate():
    seq = sequential_search(NQueensProblem(N=8))
    ds = dist_search(NQueensProblem(N=8), m=5, M=128, num_hosts=1)
    assert ds.explored_sol == seq.explored_sol
    assert ds.explored_tree == seq.explored_tree
