"""Runtime guard mode (TTS_GUARD / --guard): steady-state resident cycles
must neither recompile nor transfer (ISSUE 1 acceptance criterion)."""

from __future__ import annotations

import numpy as np
import pytest

from tpu_tree_search.analysis.guard import (
    GuardViolation,
    SteadyStateGuard,
    guard_enabled,
)
from tpu_tree_search.engine import sequential_search
from tpu_tree_search.engine.resident import resident_search
from tpu_tree_search.problems import NQueensProblem


def test_guard_enabled_resolution(monkeypatch):
    monkeypatch.delenv("TTS_GUARD", raising=False)
    assert guard_enabled(None) is False
    assert guard_enabled(True) is True
    monkeypatch.setenv("TTS_GUARD", "1")
    assert guard_enabled(None) is True
    assert guard_enabled(False) is False  # explicit flag wins
    monkeypatch.setenv("TTS_GUARD", "0")
    assert guard_enabled(None) is False


# -- unit: the guard actually catches violations ---------------------------


def test_guard_catches_recompile():
    import jax
    import jax.numpy as jnp

    # No embedded constants: a recompile must be caught by the cache-size
    # assertion itself, not by the constant-upload transfer it may cause.
    f = jax.jit(lambda x: x + x)
    x4 = jnp.ones((4,))
    x8 = jnp.ones((8,))  # device arrays built OUTSIDE the guarded dispatch
    g = SteadyStateGuard(f, "test step")
    with g.step():
        f(x4)  # warm
    with g.step():
        f(x4)  # steady state, cached
    with pytest.raises(GuardViolation, match="recompiled"):
        with g.step():
            f(x8)  # new shape -> new executable


def test_guard_catches_implicit_transfer():
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x + 1)
    g = SteadyStateGuard(f, "test step")
    with g.step():
        f(jnp.ones((4,)))
    with pytest.raises(GuardViolation, match="implicit transfer"):
        with g.step():
            # np operand: implicit host->device upload inside the guarded
            # dispatch (exactly the regression the guard exists to catch)
            f(np.ones((4,), np.float32))


def test_guard_rearm_accepts_new_warm_dispatch():
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x - x)
    x4, x16 = jnp.ones((4,)), jnp.ones((16,))
    g = SteadyStateGuard(f, "test step")
    with g.step():
        f(x4)
    g.rearm()
    with g.step():  # warm again: recompile is sanctioned
        f(x16)
    with g.step():
        f(x16)


def test_guard_disabled_is_noop():
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x + 1)
    g = SteadyStateGuard(f, "test step", enabled=False)
    for shape in ((4,), (8,), (16,)):  # would violate if enabled
        with g.step():
            f(np.ones(shape, np.float32))
    assert g.steps == 0


# -- the acceptance-criterion run -----------------------------------------


def test_resident_steady_state_is_pure_under_guard():
    """N > 1 steady-state cycles with zero recompilations and zero implicit
    transfers: a guarded resident run completes (any violation raises) and
    provably dispatched more than one K-block."""
    p = NQueensProblem(N=9)
    res = resident_search(p, m=25, M=128, K=2, guard=True)
    assert res.complete
    # kernel_launches counts device chunk cycles; > K proves more than one
    # host dispatch ran, i.e. the guarded steady-state path was exercised.
    assert res.diagnostics.kernel_launches > 2
    seq = sequential_search(NQueensProblem(N=9))
    assert res.explored_tree == seq.explored_tree
    assert res.explored_sol == seq.explored_sol


def test_resident_guard_env_knob(monkeypatch):
    monkeypatch.setenv("TTS_GUARD", "1")
    res = resident_search(NQueensProblem(N=8), m=25, M=64, K=2)
    assert res.complete and res.diagnostics.kernel_launches > 2


def test_mesh_resident_guard():
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs the virtual multi-device CPU platform")
    from tpu_tree_search.parallel.resident_mesh import mesh_resident_search

    res = mesh_resident_search(
        NQueensProblem(N=9), m=5, M=64, K=2, D=2, guard=True
    )
    assert res.complete
    seq = sequential_search(NQueensProblem(N=9))
    assert res.explored_sol == seq.explored_sol


def test_cli_guard_flag_rejected_off_resident_tiers():
    from tpu_tree_search import cli

    with pytest.raises(SystemExit):
        cli.main(["nqueens", "--N", "8", "--tier", "seq", "--guard"])
    with pytest.raises(SystemExit):
        cli.main(["nqueens", "--N", "8", "--tier", "device",
                  "--engine", "offload", "--guard"])
