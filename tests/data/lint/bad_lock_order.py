"""Known-bad fixture for the ``lock-order`` audit: an A->B / B->A blocking
cycle (deadlock potential) and a blocking same-class re-acquisition (must
be try_lock).  Every guarded access holds its own lock, so only the
lock-order rule fires here."""

import threading


class A:
    def __init__(self):
        self.lock = threading.Lock()
        self.x = 0  # guarded-by: lock


class B:
    def __init__(self):
        self.lock = threading.Lock()
        self.y = 0  # guarded-by: lock


def ab(a: A, b: B):
    with a.lock:
        with b.lock:
            b.y = 1


def ba(a: A, b: B):
    with b.lock:
        with a.lock:  # closes the A->B->A cycle
            a.x = 1


def same_class(p: A, q: A):
    with p.lock:
        with q.lock:  # blocking same-class: must be try_lock
            q.x = 2


def sanctioned(p: A, q: A):
    with p.lock:
        if q.try_lock():  # non-blocking probe: the steal discipline — OK
            q.x = 3
