"""Known-bad fixture: unlocked access to guarded-by annotated state."""

import threading


class Counter:
    # guarded-by: lock -- value, history
    # requires-lock: lock -- bump_unlocked

    def __init__(self):
        self.lock = threading.Lock()
        self.value = 0
        self.history: list[int] = []

    def bump(self):
        with self.lock:
            self.value += 1  # OK: under the matching lock

    def bump_unlocked(self):
        self.value += 1  # OK: requires-lock contract (call sites checked)

    def try_lock(self) -> bool:
        return self.lock.acquire(blocking=False)

    def unlock(self):
        self.lock.release()

    def peek(self) -> int:
        return self.value  # BAD: unlocked read in a non-contract method


def race(counters: list[Counter]):
    c = counters[0]
    c.value += 1  # BAD: unlocked write through a typed base
    c.bump_unlocked()  # BAD: requires-lock call without the lock
    with c.lock:
        c.value += 1  # OK
        c.bump_unlocked()  # OK
    if c.try_lock():
        c.value -= 1  # OK: try_lock taken branch
    for other in counters:
        other.history.append(1)  # BAD: unlocked read of guarded field
    big = max(counters)
    return big.value  # BAD: unlocked read via min/max element inference


def waived(c: Counter) -> int:
    # tts-lint: waive guarded-by -- advisory racy read, re-checked under lock
    return c.value
