"""Known-bad fixture: Python-scalar params of jitted entry points not
declared static."""

from functools import partial

import jax
import jax.numpy as jnp


@jax.jit
def decorated(pool, m: int, flip: bool):  # BAD x2: m, flip dynamic
    return pool[:1] if flip else pool


@partial(jax.jit, static_argnames=("m",))
def partial_ok(pool, m: int):  # OK: m declared static
    return pool * m


def stepper(pool, k: int, best):  # BAD: k dynamic at the jit call site
    return pool + k + best


step = jax.jit(stepper, donate_argnums=(0,))


def clean(pool, best):  # OK: no scalar-annotated params
    return jnp.minimum(pool, best)


clean_jit = jax.jit(clean)
