"""Known-bad fixture: Python control flow on traced values."""

import jax
import jax.numpy as jnp


@jax.jit
def prune(bounds, best):
    if bounds.min() < best:  # BAD: `if` on traced comparison
        best = bounds.min()
    n = 0
    while best > 0:  # BAD: `while` on traced value
        n += 1
    size = bounds.shape[0]
    if size > 128:  # OK: shape metadata is static
        return best
    return jnp.minimum(best, 0)


def kernel(x, flag):
    y = x * 2
    z = y + 1
    if z.sum() > 0:  # BAD: derived traced value (assignment chain)
        return z
    if flag is None:  # OK: identity check is static
        return x
    return y


wrapped = jax.jit(kernel)


from functools import partial


@partial(jax.jit, static_argnames=("k",))
def rebound(x, k: int):
    k = x.sum()  # rebind: the static name now carries a traced value
    if k > 0:  # BAD: branch on the re-tainted name
        return x
    return x * 2
