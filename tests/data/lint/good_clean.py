"""Known-good fixture: device-idiomatic code that must produce no findings."""

import threading

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


@jax.jit
def step(pool, size, best):
    cnt = jnp.minimum(size, 8)
    bounds = pool[:8].sum(axis=-1)
    best = jnp.minimum(best, jnp.min(bounds))
    keep = bounds < best
    return pool, size - cnt, best, keep


def host_driver(pool_np, best: int):
    # Host-side code may sync freely: none of this is traced.
    arr = np.asarray(pool_np)
    total = int(arr.sum())
    if total > 0:
        best = min(best, total)
    return float(best)


class Pool:
    # guarded-by: lock -- size

    def __init__(self):
        self.lock = threading.Lock()
        self.size = 0


def consume(p: Pool) -> int:
    with p.lock:
        return p.size


def shapes(x):
    n = x.shape[-1]
    if n <= 32:  # static: shape metadata
        return x.reshape(n, -1)
    return x


shaped = jax.jit(shapes)


from functools import partial


@partial(jax.jit, static_argnames=("block",))
def blocked(x, block: int = 1):
    # static_argnames params are Python ints at trace time: branching on
    # them specializes the compiled program (the pair-block pattern).
    if block > 1:
        for b in range(x.shape[0] // block):
            x = x + b
        return x
    acc = block * 2
    while acc < 8:  # static: derived from the static param only
        acc *= 2
    return x * acc


@partial(jax.jit, static_argnums=(1,))
def blocked_by_num(x, block):
    if block > 1:  # static via static_argnums -> positional name
        return x * block
    return x
