"""Known-bad fixture: host syncs reachable inside traced code."""

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


@jax.jit
def decorated_step(pool, size):
    total = pool.sum()
    return total.item()  # BAD: .item() inside jit


def helper(x):
    return float(x) + 1.0  # BAD via call closure: float() on traced arg


def body(carry):
    x, i = carry
    np.asarray(x)  # BAD: np.asarray inside while_loop body
    return helper(x), i + 1


def cond(carry):
    return carry[1] < 10


def run(x):
    return lax.while_loop(cond, body, (x, 0))


def bound_step(pool, best):
    jax.device_get(best)  # BAD: device_get in jitted fn
    pool.block_until_ready()  # BAD: sync in jitted fn
    return pool.min(best)


run_jit = jax.jit(bound_step, donate_argnums=(0,))


# tts-lint: traced
def marked(frontier):
    return int(frontier[0])  # BAD: int() on traced value (marker form)
