"""Survivor-path overhaul (`ops/compaction.py` + the fused prune+push in
`engine/resident.py`): dense-path bit-exactness against the scatter oracle,
the structural pins the acceptance criteria demand (dense programs free of
sort/scatter; at most ONE child-value-sized gather per cycle in every
mode) — routed through the contract registry (`tts check`,
analysis/contracts.py) since ISSUE 8, so the same claims are also checked
over the whole knob matrix — plus the auto policy and the push_rows
telemetry."""

from __future__ import annotations

import numpy as np
import pytest

from tpu_tree_search.analysis import contracts, program_audit
from tpu_tree_search.engine.resident import resident_search
from tpu_tree_search.engine.sequential import sequential_search
from tpu_tree_search.ops import compaction
from tpu_tree_search.problems import NQueensProblem, PFSPProblem
from tpu_tree_search.problems.pfsp import taillard

program_audit.load_contracts()


# -- dense ids vs the scatter oracle ---------------------------------------


def _oracle_ids(keep, S):
    """Host-side reference: survivors' flat ids in (parent, slot) order."""
    flat = keep.reshape(-1)
    return np.nonzero(flat)[0][:S], int(flat.sum())


@pytest.mark.parametrize("shape,seed", [
    ((64, 20), 3),      # the M=1k class (64 parents x 20 slots per case)
    ((1024, 20), 7),    # M=1k headline shape
    ((65536, 8), 11),   # M=64k grid — the N-Queens chunk class
])
def test_dense_ids_bitexact_vs_scatter_oracle(shape, seed):
    rng = np.random.default_rng(seed)
    densities = (0.0, 0.03, 0.5, 0.97, 1.0)
    for p in densities:
        keep = rng.random(shape) < p
        S = keep.size if keep.size <= 20_000 else keep.size // 2
        ids_d, inc_d = (np.asarray(x) for x in
                        compaction.compact_ids(keep, S, "dense"))
        ids_sc, inc_sc = (np.asarray(x) for x in
                          compaction.compact_ids(keep, S, "scatter"))
        ref, inc_ref = _oracle_ids(keep, S)
        assert inc_d == inc_sc == inc_ref
        k = min(inc_ref, S)
        np.testing.assert_array_equal(ids_d[:k], ref[:k])
        np.testing.assert_array_equal(ids_sc[:k], ref[:k])
        # Dead rows stay in-bounds (the pool contract's only requirement).
        assert (0 <= ids_d).all() and (ids_d < keep.size).all()


def test_dense_ids_edge_masks():
    for keep in (np.zeros((1, 7), bool), np.ones((5, 3), bool),
                 np.eye(9, 9, dtype=bool)):
        S = keep.size
        ids_d, inc = (np.asarray(x) for x in
                      compaction.compact_ids(keep, S, "dense"))
        ref, inc_ref = _oracle_ids(keep, S)
        assert inc == inc_ref
        np.testing.assert_array_equal(ids_d[:inc], ref)


# -- structural pins: routed through the contract registry (ISSUE 8) -------
# The claims below are Contracts declared in ops/compaction.py and
# engine/resident.py and checked over the WHOLE knob matrix by `tts
# check`; these tests exercise the same registry entries on the cells
# this file historically guarded, so a local run still fails fast.


@pytest.mark.parametrize("family", ["nqueens", "pfsp-lb1"])
def test_dense_step_contract_free_of_sort_scatter(family):
    """The acceptance pin: under TTS_COMPACT=dense the WHOLE compiled step
    — compaction, fused push, and the overflow fallback branch — adds no
    sort and no scatter beyond the bare evaluator's own ops."""
    cell = program_audit.Cell(family, compact="dense")
    art = program_audit.trace_cell(cell)
    assert art.prog.compact == "dense"
    assert contracts.run_one("dense-step-no-sort-scatter", art, cell) == []


def test_compact_ids_contracts():
    """The dense rank inversion is pure shifts + selects (no sort, no
    scatter, not even a gather) and the scatter inversion's one scatter
    is genuinely unique-indexed — both registry entries, all modes."""
    findings = program_audit.audit_compact_ids()
    assert findings == [], [f.render() for f in findings]


@pytest.mark.parametrize("mode", ["scatter", "sort", "search", "dense"])
def test_fused_push_single_child_value_gather(mode):
    """Op-count pin for the fused prune+push: in EVERY mode at most one
    gather big enough to be moving child values (>= S rows of n lanes in
    the pool value dtype) — the single augmented (row, aux) gather of the
    fused write.  The pre-fusion body gathered rows, both swap lanes, and
    aux separately."""
    cell = program_audit.Cell("pfsp-lb1", compact=mode)
    art = program_audit.trace_cell(cell)
    assert contracts.run_one("fused-push-single-gather", art, cell) == []


def test_auto_resolves_identically_to_explicit():
    """TTS_COMPACT=auto must bake in the same program as the explicitly
    spelled mode it resolves to — byte-identical jaxpr, so the policy
    layer adds zero behavior of its own."""
    art = program_audit.variant_artifact("nqueens", labels=["compact-auto"])
    assert "compact-dense" in art.variants  # the policy pick for N-Queens
    assert contracts.run_one("compact-auto-identity", art) == []


# -- auto policy ------------------------------------------------------------


def test_auto_policy_table(monkeypatch):
    monkeypatch.setenv("TTS_COMPACT", "auto")
    nq = NQueensProblem(N=10)
    pf1 = PFSPProblem(inst=14, lb="lb1", ub=1)  # pruned regime (opt UB)
    pf0 = PFSPProblem(lb="lb1", ub=0,           # no-prune regime (inf UB)
                      p_times=taillard.reduced_instance(14, 10, 5))
    # N-Queens: dense on every backend (no pruning — dense survivors).
    assert compaction.resolve_compact_mode(nq, 65536, 10) == "dense"
    # Non-TPU backends keep the measured CPU default for PFSP.
    assert compaction._auto_compact(pf1, 1024, 20, "cpu") == "scatter"
    # TPU: small grids and the no-prune (ub=inf) regime go dense; large
    # pruned grids take the binary-search inverse.
    assert compaction._auto_compact(pf1, 1024, 20, "tpu") == "dense"
    assert compaction._auto_compact(pf1, 65536, 20, "tpu") == "search"
    assert compaction._auto_compact(pf0, 65536, 20, "tpu") == "dense"
    # An explicit knob always wins over the policy.
    monkeypatch.setenv("TTS_COMPACT", "sort")
    assert compaction.resolve_compact_mode(nq, 65536, 10) == "sort"
    # Bad knob values fail loudly.
    monkeypatch.setenv("TTS_COMPACT", "bogus")
    with pytest.raises(ValueError):
        compaction.compact_mode()


def test_auto_knob_flip_rebuilds_program_same_instance(monkeypatch):
    """auto <-> explicit flips between searches on ONE problem instance
    must rebuild the resident program (the raw knob is part of the routing
    token), and both runs must land identical counts."""
    prob = NQueensProblem(N=9)
    seq = sequential_search(prob)
    monkeypatch.setenv("TTS_COMPACT", "auto")
    r1 = resident_search(prob, m=8, M=128, K=32)
    n_after = len(prob._resident_programs)
    monkeypatch.setenv("TTS_COMPACT", "search")
    r2 = resident_search(prob, m=8, M=128, K=32)
    assert len(prob._resident_programs) == n_after + 1
    assert r1.compact == "dense" and r1.compact_auto
    assert r2.compact == "search" and not r2.compact_auto
    for r in (r1, r2):
        assert (r.explored_tree, r.explored_sol) == (
            seq.explored_tree, seq.explored_sol)


# -- end-to-end dense parity (both problems, overflow branch included) ------


def test_dense_end_to_end_parity_both_problems(monkeypatch):
    monkeypatch.setenv("TTS_COMPACT", "dense")
    prob = NQueensProblem(N=10)
    seq = sequential_search(prob)
    res = resident_search(NQueensProblem(N=10), m=8, M=1024, K=64)
    assert (res.explored_tree, res.explored_sol) == (
        seq.explored_tree, seq.explored_sol)
    assert res.compact == "dense" and not res.compact_auto

    ptm = taillard.reduced_instance(14, jobs=10, machines=5)
    opt = sequential_search(PFSPProblem(lb="lb1", ub=0, p_times=ptm)).best
    seqp = sequential_search(PFSPProblem(lb="lb1", ub=0, p_times=ptm),
                             initial_best=opt)
    resp = resident_search(PFSPProblem(lb="lb1", ub=0, p_times=ptm),
                           m=8, M=1024, K=64, initial_best=opt)
    assert (resp.explored_tree, resp.explored_sol, resp.best) == (
        seqp.explored_tree, seqp.explored_sol, opt)


def test_dense_overflow_branch_parity(monkeypatch):
    """Force the dense overflow path (survivors > S): shallow N-Queens
    chunks keep ~M*(N-d) children >> S = M*N/2 — the shift-compacted
    full-row write must land the sequential goldens exactly, scatter-free."""
    monkeypatch.setenv("TTS_COMPACT", "dense")
    prob = NQueensProblem(N=11)
    seq = sequential_search(prob)
    res = resident_search(NQueensProblem(N=11), m=8, M=512, K=8)
    assert (res.explored_tree, res.explored_sol) == (
        seq.explored_tree, seq.explored_sol)


# -- telemetry: the maintenance/evaluator split -----------------------------


def test_push_rows_counter_and_report_split(monkeypatch):
    from tpu_tree_search.obs import capture, report

    monkeypatch.setenv("TTS_COMPACT", "dense")
    monkeypatch.setenv("TTS_OBS", "1")
    with capture() as cap:
        res = resident_search(NQueensProblem(N=9), m=5, M=128)
    c = res.obs["device_counters"]
    # The fused path processes its full S budget per cycle: push_rows is
    # the maintenance-work series and can never undercount the survivors.
    assert c["push_rows"] >= c["pushed"] > 0
    s = report.summarize(cap.events)["survivor_path"]
    assert s["push_rows"] == c["push_rows"]
    assert s["eval_rows"] == c["pushed"] + c["leaves"] + c["pruned"]
    assert s["push_rows_per_survivor"] >= 1.0
