"""Survivor-path overhaul (`ops/compaction.py` + the fused prune+push in
`engine/resident.py`): dense-path bit-exactness against the scatter oracle,
the jaxpr pins the acceptance criteria demand (dense programs free of
sort/scatter; at most ONE child-value-sized gather per cycle in every
mode), the auto policy, and the push_rows telemetry."""

from __future__ import annotations

import numpy as np
import pytest

from tpu_tree_search.engine.resident import (
    _compact_ids,
    _make_program,
    resident_search,
    resolve_capacity,
)
from tpu_tree_search.engine.sequential import sequential_search
from tpu_tree_search.ops import compaction
from tpu_tree_search.problems import NQueensProblem, PFSPProblem
from tpu_tree_search.problems.pfsp import taillard


# -- dense ids vs the scatter oracle ---------------------------------------


def _oracle_ids(keep, S):
    """Host-side reference: survivors' flat ids in (parent, slot) order."""
    flat = keep.reshape(-1)
    return np.nonzero(flat)[0][:S], int(flat.sum())


@pytest.mark.parametrize("shape,seed", [
    ((64, 20), 3),      # the M=1k class (64 parents x 20 slots per case)
    ((1024, 20), 7),    # M=1k headline shape
    ((65536, 8), 11),   # M=64k grid — the N-Queens chunk class
])
def test_dense_ids_bitexact_vs_scatter_oracle(shape, seed):
    rng = np.random.default_rng(seed)
    densities = (0.0, 0.03, 0.5, 0.97, 1.0)
    for p in densities:
        keep = rng.random(shape) < p
        S = keep.size if keep.size <= 20_000 else keep.size // 2
        ids_d, inc_d = (np.asarray(x) for x in
                        compaction.compact_ids(keep, S, "dense"))
        ids_sc, inc_sc = (np.asarray(x) for x in
                          compaction.compact_ids(keep, S, "scatter"))
        ref, inc_ref = _oracle_ids(keep, S)
        assert inc_d == inc_sc == inc_ref
        k = min(inc_ref, S)
        np.testing.assert_array_equal(ids_d[:k], ref[:k])
        np.testing.assert_array_equal(ids_sc[:k], ref[:k])
        # Dead rows stay in-bounds (the pool contract's only requirement).
        assert (0 <= ids_d).all() and (ids_d < keep.size).all()


def test_dense_ids_edge_masks():
    for keep in (np.zeros((1, 7), bool), np.ones((5, 3), bool),
                 np.eye(9, 9, dtype=bool)):
        S = keep.size
        ids_d, inc = (np.asarray(x) for x in
                      compaction.compact_ids(keep, S, "dense"))
        ref, inc_ref = _oracle_ids(keep, S)
        assert inc == inc_ref
        np.testing.assert_array_equal(ids_d[:inc], ref)


# -- jaxpr pins -------------------------------------------------------------


def _prim_names(jaxpr, out=None):
    """Every primitive name in a (closed) jaxpr, recursing into sub-jaxprs
    (while/cond/scan/pjit bodies)."""
    if out is None:
        out = []
    for eqn in jaxpr.eqns:
        out.append((eqn.primitive.name, eqn))
        for v in eqn.params.values():
            for sub in _as_jaxprs(v):
                _prim_names(sub, out)
    return out


def _as_jaxprs(v):
    from jax.core import ClosedJaxpr, Jaxpr

    if isinstance(v, Jaxpr):
        return [v]
    if isinstance(v, ClosedJaxpr):
        return [v.jaxpr]
    if isinstance(v, (list, tuple)):
        return [j for x in v for j in _as_jaxprs(x)]
    return []


def _step_prims(problem, M, K=4, monkeypatch=None, mode=None):
    import jax

    if mode is not None:
        monkeypatch.setenv("TTS_COMPACT", mode)
    capacity, M = resolve_capacity(problem, M, None)
    prog = _make_program(problem, 5, M, K, capacity, jax.devices()[0])
    state = prog.init_state({}, getattr(problem, "initial_ub", 0))
    jaxpr = jax.make_jaxpr(prog._step)(*state)
    return prog, _prim_names(jaxpr.jaxpr)


@pytest.mark.parametrize("mk", [
    lambda: NQueensProblem(N=9),
    lambda: PFSPProblem(lb="lb1", ub=0,
                        p_times=taillard.reduced_instance(14, 10, 5)),
])
def test_dense_step_jaxpr_free_of_sort_scatter(mk, monkeypatch):
    """The acceptance pin: under TTS_COMPACT=dense the WHOLE compiled step
    — compaction, fused push, and the overflow fallback branch — contains
    no sort, no scatter, and no searchsorted (searchsorted has no
    primitive of its own; banning sort+scatter plus the compact_ids-level
    gather pin below covers every implementation it could lower to)."""
    _, prims = _step_prims(mk(), 128, monkeypatch=monkeypatch, mode="dense")
    names = {n for n, _ in prims}
    assert not any(n.startswith("scatter") for n in names), names
    assert "sort" not in names, names


def test_dense_compact_ids_jaxpr_gather_free(monkeypatch):
    """The dense rank inversion itself is pure shifts + selects: no sort,
    no scatter, and not even a gather (the fused write performs the
    cycle's single gather)."""
    import jax

    jaxpr = jax.make_jaxpr(
        lambda k: compaction.compact_ids(k, 640, "dense")
    )(np.zeros((64, 20), bool))
    names = {n for n, _ in _prim_names(jaxpr.jaxpr)}
    for banned in ("sort", "gather"):
        assert banned not in names, names
    assert not any(n.startswith("scatter") for n in names), names


@pytest.mark.parametrize("mode", ["scatter", "sort", "search", "dense"])
def test_fused_push_single_child_value_gather(mode, monkeypatch):
    """Op-count pin for the fused prune+push: in EVERY mode the compiled
    step contains at most one gather big enough to be moving child values
    (>= S rows of n lanes) — the single augmented (row, aux) gather of the
    fused write.  The pre-fusion body gathered rows, both swap lanes, and
    aux separately."""
    prob = PFSPProblem(lb="lb1", ub=0,
                       p_times=taillard.reduced_instance(14, 10, 5))
    prog, prims = _step_prims(prob, 128, monkeypatch=monkeypatch, mode=mode)
    n = prob.child_slots
    vals_dt = np.dtype(prog.pool_fields[0][1])
    # "Child values" = pool-value-dtype rows; the search mode additionally
    # gathers (S, n) keep/lane MASKS by design, which move no node data.
    big = [
        eqn for name, eqn in prims
        if name == "gather"
        and any(v.aval.size >= prog.S * n and v.aval.dtype == vals_dt
                for v in eqn.outvars)
    ]
    assert len(big) <= 1, (mode, [str(e) for e in big])


def test_auto_resolves_identically_to_explicit(monkeypatch):
    """TTS_COMPACT=auto must bake in the same program as the explicitly
    spelled mode it resolves to — byte-identical jaxpr, so the policy
    layer adds zero behavior of its own."""
    import jax

    def jaxpr_text(mode):
        monkeypatch.setenv("TTS_COMPACT", mode)
        prob = NQueensProblem(N=8)  # fresh instance: no cached programs
        capacity, M = resolve_capacity(prob, 64, None)
        prog = _make_program(prob, 5, M, 4, capacity, jax.devices()[0])
        assert prog.compact == "dense"  # the policy pick for N-Queens
        state = prog.init_state({}, 0)
        return str(jax.make_jaxpr(prog._step)(*state))

    assert jaxpr_text("auto") == jaxpr_text("dense")


# -- auto policy ------------------------------------------------------------


def test_auto_policy_table(monkeypatch):
    monkeypatch.setenv("TTS_COMPACT", "auto")
    nq = NQueensProblem(N=10)
    pf1 = PFSPProblem(inst=14, lb="lb1", ub=1)  # pruned regime (opt UB)
    pf0 = PFSPProblem(lb="lb1", ub=0,           # no-prune regime (inf UB)
                      p_times=taillard.reduced_instance(14, 10, 5))
    # N-Queens: dense on every backend (no pruning — dense survivors).
    assert compaction.resolve_compact_mode(nq, 65536, 10) == "dense"
    # Non-TPU backends keep the measured CPU default for PFSP.
    assert compaction._auto_compact(pf1, 1024, 20, "cpu") == "scatter"
    # TPU: small grids and the no-prune (ub=inf) regime go dense; large
    # pruned grids take the binary-search inverse.
    assert compaction._auto_compact(pf1, 1024, 20, "tpu") == "dense"
    assert compaction._auto_compact(pf1, 65536, 20, "tpu") == "search"
    assert compaction._auto_compact(pf0, 65536, 20, "tpu") == "dense"
    # An explicit knob always wins over the policy.
    monkeypatch.setenv("TTS_COMPACT", "sort")
    assert compaction.resolve_compact_mode(nq, 65536, 10) == "sort"
    # Bad knob values fail loudly.
    monkeypatch.setenv("TTS_COMPACT", "bogus")
    with pytest.raises(ValueError):
        compaction.compact_mode()


def test_auto_knob_flip_rebuilds_program_same_instance(monkeypatch):
    """auto <-> explicit flips between searches on ONE problem instance
    must rebuild the resident program (the raw knob is part of the routing
    token), and both runs must land identical counts."""
    prob = NQueensProblem(N=9)
    seq = sequential_search(prob)
    monkeypatch.setenv("TTS_COMPACT", "auto")
    r1 = resident_search(prob, m=8, M=128, K=32)
    n_after = len(prob._resident_programs)
    monkeypatch.setenv("TTS_COMPACT", "search")
    r2 = resident_search(prob, m=8, M=128, K=32)
    assert len(prob._resident_programs) == n_after + 1
    assert r1.compact == "dense" and r1.compact_auto
    assert r2.compact == "search" and not r2.compact_auto
    for r in (r1, r2):
        assert (r.explored_tree, r.explored_sol) == (
            seq.explored_tree, seq.explored_sol)


# -- end-to-end dense parity (both problems, overflow branch included) ------


def test_dense_end_to_end_parity_both_problems(monkeypatch):
    monkeypatch.setenv("TTS_COMPACT", "dense")
    prob = NQueensProblem(N=10)
    seq = sequential_search(prob)
    res = resident_search(NQueensProblem(N=10), m=8, M=1024, K=64)
    assert (res.explored_tree, res.explored_sol) == (
        seq.explored_tree, seq.explored_sol)
    assert res.compact == "dense" and not res.compact_auto

    ptm = taillard.reduced_instance(14, jobs=10, machines=5)
    opt = sequential_search(PFSPProblem(lb="lb1", ub=0, p_times=ptm)).best
    seqp = sequential_search(PFSPProblem(lb="lb1", ub=0, p_times=ptm),
                             initial_best=opt)
    resp = resident_search(PFSPProblem(lb="lb1", ub=0, p_times=ptm),
                           m=8, M=1024, K=64, initial_best=opt)
    assert (resp.explored_tree, resp.explored_sol, resp.best) == (
        seqp.explored_tree, seqp.explored_sol, opt)


def test_dense_overflow_branch_parity(monkeypatch):
    """Force the dense overflow path (survivors > S): shallow N-Queens
    chunks keep ~M*(N-d) children >> S = M*N/2 — the shift-compacted
    full-row write must land the sequential goldens exactly, scatter-free."""
    monkeypatch.setenv("TTS_COMPACT", "dense")
    prob = NQueensProblem(N=11)
    seq = sequential_search(prob)
    res = resident_search(NQueensProblem(N=11), m=8, M=512, K=8)
    assert (res.explored_tree, res.explored_sol) == (
        seq.explored_tree, seq.explored_sol)


# -- telemetry: the maintenance/evaluator split -----------------------------


def test_push_rows_counter_and_report_split(monkeypatch):
    from tpu_tree_search.obs import capture, report

    monkeypatch.setenv("TTS_COMPACT", "dense")
    monkeypatch.setenv("TTS_OBS", "1")
    with capture() as cap:
        res = resident_search(NQueensProblem(N=9), m=5, M=128)
    c = res.obs["device_counters"]
    # The fused path processes its full S budget per cycle: push_rows is
    # the maintenance-work series and can never undercount the survivors.
    assert c["push_rows"] >= c["pushed"] > 0
    s = report.summarize(cap.events)["survivor_path"]
    assert s["push_rows"] == c["push_rows"]
    assert s["eval_rows"] == c["pushed"] + c["leaves"] + c["pruned"]
    assert s["push_rows_per_survivor"] >= 1.0
