"""Search-quality telemetry (obs/quality.py + problems/taillard_optima.py):
the incumbent trajectory, primal gap/integral math, the committed
best-known table, engine wiring, and the quality-off byte-identity
contract.

Everything runs on the virtual CPU platform with small shapes; the
identity claims are the same registry entries `tts check` audits over
the full knob matrix.
"""

from __future__ import annotations

import json

import pytest

from tpu_tree_search.obs import quality
from tpu_tree_search.problems import taillard_optima
from tpu_tree_search.problems.base import INF_BOUND
from tpu_tree_search.problems.nqueens import NQueensProblem


# -- the committed best-known table -----------------------------------------


def test_every_bundled_instance_has_an_entry():
    # The generator covers ta001..ta120; the reference table must too —
    # a gap here silently turns a quality curve's gap column into "?".
    for inst in range(1, 121):
        v = taillard_optima.known_optimum(inst)
        assert isinstance(v, int) and v > 0, f"ta{inst:03d} missing"


def test_table_spot_values_and_provenance_consistency():
    # Spot values from Taillard's published tables.
    assert taillard_optima.known_optimum(1) == 1278
    assert taillard_optima.known_optimum(14) == 1377
    assert taillard_optima.known_optimum(21) == 2297
    assert taillard_optima.known_optimum(120) == 26457
    # The engine's initial-UB table (pfsp/taillard.py, from c_taillard.c)
    # must agree entry-for-entry: both derive from the same source, and a
    # drift between them would mean gaps measured against a moving UB.
    from tpu_tree_search.problems.pfsp import taillard

    for inst in range(1, 121):
        assert (taillard_optima.known_optimum(inst)
                == taillard.OPTIMAL_MAKESPANS[inst - 1]), inst


def test_unknown_instances_are_none_not_errors():
    assert taillard_optima.known_optimum(0) is None
    assert taillard_optima.known_optimum(121) is None
    assert taillard_optima.known_optimum("ta014") is None
    assert taillard_optima.known_optimum(None) is None


def test_optimum_for_problem_objects():
    class FakePfsp:
        name = "pfsp"
        inst = 14

    class FakeOther:
        name = "nqueens"

    assert taillard_optima.optimum_for(FakePfsp()) == 1377
    assert taillard_optima.optimum_for(FakeOther()) is None
    assert taillard_optima.optimum_for(None) is None


def test_gap_semantics():
    assert taillard_optima.gap(1377, 1377) == 0.0
    assert taillard_optima.gap(1515, 1377) == pytest.approx(138 / 1377)
    # Cleanly None on every unknown: no incumbent yet, no reference, or
    # a nonsense reference.
    assert taillard_optima.gap(None, 1377) is None
    assert taillard_optima.gap(INF_BOUND, 1377) is None
    assert taillard_optima.gap(1500, None) is None
    assert taillard_optima.gap(1500, 0) is None


# -- recorder semantics ------------------------------------------------------


def test_recorder_first_observation_always_records():
    rec = quality.QualityRecorder()
    assert rec.observe(INF_BOUND, 1, 100)  # anchors the curve
    assert not rec.observe(INF_BOUND, 2, 200)  # no improvement
    assert rec.observe(50, 3, 300)
    assert not rec.observe(50, 4, 400)
    pts = rec.points()
    assert [p["best"] for p in pts] == [INF_BOUND, 50]
    assert pts[0]["t_s"] == 0.0  # time base = first observation


def test_recorder_step_offset_spans_slices():
    # The serve scheduler sets step_offset to the job's cumulative steps
    # before each slice, so recorded steps stay job-cumulative.
    rec = quality.QualityRecorder()
    rec.observe(100, 5, 10)
    rec.step_offset = 40
    rec.observe(90, 5, 20)  # slice-local step 5 == job step 45
    assert [p["step"] for p in rec.points()] == [5, 45]


def test_recorder_result_payload():
    rec = quality.QualityRecorder(optimum=1377)
    rec.observe(1500, 1, 10)
    out = rec.result()
    assert out["optimum"] == 1377
    assert out["points"][0]["best"] == 1500
    json.dumps(out)  # the payload must be JSON-serializable as-is


# -- tracker arming ----------------------------------------------------------


def test_tracker_off_by_default(monkeypatch):
    monkeypatch.delenv("TTS_QUALITY", raising=False)
    assert quality.tracker() is None


def test_tracker_armed_by_knob(monkeypatch):
    monkeypatch.setenv("TTS_QUALITY", "1")
    rec = quality.tracker()
    assert isinstance(rec, quality.QualityRecorder)


def test_tracker_bound_recorder_wins_and_resolves_optimum(monkeypatch):
    monkeypatch.delenv("TTS_QUALITY", raising=False)

    class FakePfsp:
        name = "pfsp"
        inst = 14

    mine = quality.QualityRecorder()
    with quality.bound(mine):
        got = quality.tracker(FakePfsp())
        assert got is mine and got.optimum == 1377
    assert quality.tracker(FakePfsp()) is None  # binding restored


# -- primal integral ---------------------------------------------------------


def test_primal_integral_step_function():
    # Optimal found at t=0.5 of a 1s horizon: gap is cap (1.0) for the
    # first half, 0 after -> integral 0.5.
    pts = [{"t_s": 0.5, "best": 100}]
    assert quality.primal_integral(pts, 100, 1.0) == pytest.approx(0.5)
    # Never found anything: flat at cap.
    assert quality.primal_integral([], 100, 1.0) == pytest.approx(1.0)
    # 10% gap from t=0: flat at 0.1.
    pts = [{"t_s": 0.0, "best": 110}]
    assert quality.primal_integral(pts, 100, 2.0) == pytest.approx(0.1)
    # Two-step descent.
    pts = [{"t_s": 0.0, "best": 150}, {"t_s": 1.0, "best": 100}]
    assert quality.primal_integral(pts, 100, 2.0) == pytest.approx(0.25)


def test_primal_integral_unknowns_and_caps():
    assert quality.primal_integral([], None, 1.0) is None
    assert quality.primal_integral([], 100, 0.0) is None
    # An INF incumbent (N-Queens sentinel) counts as cap, not a crash.
    pts = [{"t_s": 0.0, "best": INF_BOUND}]
    assert quality.primal_integral(pts, 100, 1.0) == pytest.approx(1.0)
    # Gaps above cap clamp to cap.
    pts = [{"t_s": 0.0, "best": 1000}]
    assert quality.primal_integral(pts, 100, 1.0) == pytest.approx(1.0)


# -- engine wiring -----------------------------------------------------------


def test_resident_quality_trajectory_and_bit_identity(monkeypatch):
    from tpu_tree_search.engine.resident import resident_search

    monkeypatch.delenv("TTS_QUALITY", raising=False)
    off = resident_search(NQueensProblem(N=8), m=5, M=64)
    assert off.quality is None  # off by default — nothing recorded
    monkeypatch.setenv("TTS_QUALITY", "1")
    on = resident_search(NQueensProblem(N=8), m=5, M=64)
    # Telemetry must not perturb the search: same totals, same result.
    assert (on.explored_tree, on.explored_sol, on.best) == (
        off.explored_tree, off.explored_sol, off.best)
    assert on.quality is not None and on.quality["points"]
    p0 = on.quality["points"][0]
    assert p0["best"] == INF_BOUND  # N-Queens has no objective
    assert p0["nodes"] > 0 and p0["t_s"] == 0.0


@pytest.mark.slow  # pfsp resident compile dominates; CI runs it unfiltered
def test_pfsp_quality_curve_has_gap(monkeypatch):
    from tpu_tree_search.engine.resident import resident_search
    from tpu_tree_search.problems import PFSPProblem

    monkeypatch.setenv("TTS_QUALITY", "1")
    problem = PFSPProblem(inst=14, lb="lb1", ub=1)
    res = resident_search(problem, m=5, M=512, max_steps=30)
    q = res.quality
    assert q is not None and q["optimum"] == 1377
    assert q["points"], "warm-start UB should anchor the curve"
    # ub=1 starts from the optimal table value -> gap 0 at the anchor.
    g = quality.primal_gap(q["points"][0]["best"], q["optimum"])
    assert g == pytest.approx(0.0)
    pi = quality.primal_integral(q["points"], q["optimum"],
                                 max(res.elapsed, 1e-9))
    assert pi is not None and 0.0 <= pi <= 1.0


@pytest.mark.slow  # mesh compile; CI runs it unfiltered
def test_mesh_quality_trajectory(monkeypatch):
    from tpu_tree_search.parallel.resident_mesh import mesh_resident_search

    monkeypatch.setenv("TTS_QUALITY", "1")
    res = mesh_resident_search(NQueensProblem(N=8), m=5, M=64)
    assert res.quality is not None and res.quality["points"]


# -- the compiled-program contract ------------------------------------------


def test_quality_off_identity_contract():
    from tpu_tree_search.analysis import contracts, program_audit

    program_audit.load_contracts()
    art = program_audit.variant_artifact(
        "nqueens", labels=["off", "quality1"]
    )
    # Host-side-only telemetry: the TTS_QUALITY=1 step jaxpr is byte-
    # identical to the off build (same text, same outvar count).
    assert contracts.run_one("quality-off-identity", art) == []


def test_quality_knob_in_audit_matrix():
    from tpu_tree_search.analysis import program_audit

    assert "TTS_QUALITY" in program_audit.KNOBS
    assert program_audit.VARIANT_ENVS["quality1"] == {"TTS_QUALITY": "1"}
