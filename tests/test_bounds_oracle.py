"""Oracle bound sanity and internal-consistency properties.

The numpy oracles are themselves validated here by invariants the C library
satisfies (`c_bound_simple.c`, `c_bound_johnson.c`); the device kernels are
then compared against the oracles in test_device_kernels.py.
"""

import numpy as np

from tpu_tree_search.problems.pfsp import bounds as B
from tpu_tree_search.problems.pfsp import taillard as T


def _random_node(rng, jobs):
    prmu = rng.permutation(jobs).astype(np.int32)
    limit1 = int(rng.integers(-1, jobs - 1))
    return prmu, limit1


def test_lb1_leaf_equals_makespan():
    """lb1 of a complete permutation equals its makespan (SURVEY.md App. A)."""
    ptm = T.reduced_instance(14, jobs=10, machines=10)
    d = B.make_lb1(ptm)
    rng = np.random.default_rng(0)
    for _ in range(20):
        prmu = rng.permutation(10).astype(np.int32)
        assert B.lb1_bound(d, prmu, 9, 10) == B.eval_solution(d, prmu)


def test_lb1_is_lower_bound():
    """Any completion of the prefix has makespan >= lb1 of the node."""
    ptm = T.reduced_instance(3, jobs=7, machines=5)
    d = B.make_lb1(ptm)
    rng = np.random.default_rng(1)
    for _ in range(50):
        prmu, limit1 = _random_node(rng, 7)
        lb = B.lb1_bound(d, prmu, limit1, 7)
        # complete randomly several times
        for _ in range(5):
            tail = prmu[limit1 + 1 :].copy()
            rng.shuffle(tail)
            full = np.concatenate([prmu[: limit1 + 1], tail])
            assert B.eval_solution(d, full) >= lb


def test_lb2_dominates_lb1():
    """lb2 (max over machine pairs incl. adjacent ones with full Johnson) is
    at least as strong as any single 2-machine relaxation it contains; both
    must stay below the true makespan. Without early exit lb2 >= lb1 is not
    guaranteed in general, but both are valid lower bounds."""
    ptm = T.reduced_instance(14, jobs=8, machines=5)
    d1 = B.make_lb1(ptm)
    d2 = B.make_lb2(d1)
    rng = np.random.default_rng(2)
    big = 10**9
    for _ in range(30):
        prmu, limit1 = _random_node(rng, 8)
        lb2 = B.lb2_bound(d1, d2, prmu, limit1, 8, big)
        for _ in range(5):
            tail = prmu[limit1 + 1 :].copy()
            rng.shuffle(tail)
            full = np.concatenate([prmu[: limit1 + 1], tail])
            assert B.eval_solution(d1, full) >= lb2


def test_lb2_early_exit_consistency():
    """Early exit returns a value > min_cmax iff the full bound is (the prune
    decision is unchanged) — the property the TPU kernel relies on to drop
    the exit (`c_bound_johnson.c:231-234`)."""
    ptm = T.reduced_instance(21, jobs=8, machines=8)
    d1 = B.make_lb1(ptm)
    d2 = B.make_lb2(d1)
    rng = np.random.default_rng(3)
    for _ in range(50):
        prmu, limit1 = _random_node(rng, 8)
        full = B.lb2_bound(d1, d2, prmu, limit1, 8, 10**9)
        for cutoff in (full - 7, full - 1, full, full + 3):
            exited = B.lb2_bound(d1, d2, prmu, limit1, 8, cutoff)
            assert (exited > cutoff) == (full > cutoff)
            if exited <= cutoff:
                assert exited == full


def test_children_bounds_match_add_front():
    """lb1_children_bounds agrees with per-child add_front_and_bound
    (`c_bound_simple.c:160-211`)."""
    ptm = T.reduced_instance(14, jobs=9, machines=7)
    d = B.make_lb1(ptm)
    rng = np.random.default_rng(4)
    for _ in range(20):
        prmu, limit1 = _random_node(rng, 9)
        lb_begin = B.lb1_children_bounds(d, prmu, limit1, 9)
        front = B.schedule_front(d, prmu, limit1)
        back = B.schedule_back(d, prmu, 9)
        remain = B.sum_unscheduled(d, prmu, limit1, 9)
        for i in range(limit1 + 1, 9):
            job = int(prmu[i])
            assert lb_begin[job] == B.add_front_and_bound(d, job, front, back, remain)


def test_min_heads_tails_follow_c_semantics():
    """Regression guard for the Chapel min-heads port bug (SURVEY.md §2.1,
    `Bound_simple.chpl:271` vs `c_bound_simple.c:300`): heads must be the
    min over jobs of the cumulative head, not clipped at int32 max."""
    ptm = T.reduced_instance(14, jobs=6, machines=4)
    d = B.make_lb1(ptm)
    p = ptm.astype(np.int64)
    m, n = p.shape
    expect_heads = np.zeros(m, dtype=np.int64)
    for k in range(1, m):
        expect_heads[k] = min(p[:k, j].sum() for j in range(n))
    expect_tails = np.zeros(m, dtype=np.int64)
    for k in range(m - 1):
        expect_tails[k] = min(p[k + 1 :, j].sum() for j in range(n))
    assert np.array_equal(d.min_heads, expect_heads)
    assert np.array_equal(d.min_tails, expect_tails)


def test_bf16_fast_path_is_bit_exact_and_gated():
    """The single-pass bf16 MXU gather is exact iff every processing time
    < 2^8 (one-hot rows and such ints are exactly representable in bf16,
    accumulation is f32). All Taillard times are 1..99; ad-hoc instances
    with larger times must disable the fast path."""
    import jax.numpy as jnp

    from tpu_tree_search.ops import pfsp_device as P
    from tpu_tree_search.problems import PFSPProblem

    prob = PFSPProblem(inst=14, lb="lb1", ub=1)
    t = P.PFSPDeviceTables(prob.lb1_data, prob.lb2_data)
    assert t.exact_bf16 is True
    rng = np.random.default_rng(5)
    B = 64
    prmu = np.stack([rng.permutation(20).astype(np.int32) for _ in range(B)])
    l1 = rng.integers(-1, 19, B).astype(np.int32)
    for fn in (P._lb1_chunk, P._lb1_d_chunk):
        a = np.asarray(fn(jnp.asarray(prmu), jnp.asarray(l1),
                          t.ptm_t, t.min_heads, t.min_tails, bf16=False))
        b = np.asarray(fn(jnp.asarray(prmu), jnp.asarray(l1),
                          t.ptm_t, t.min_heads, t.min_tails, bf16=True))
        assert np.array_equal(a, b)
    a = np.asarray(P._lb2_chunk(jnp.asarray(prmu), jnp.asarray(l1),
                                t.ptm_t, t.min_heads, t.min_tails,
                                t.pairs, t.lags, t.johnson_schedules, bf16=False))
    b = np.asarray(P._lb2_chunk(jnp.asarray(prmu), jnp.asarray(l1),
                                t.ptm_t, t.min_heads, t.min_tails,
                                t.pairs, t.lags, t.johnson_schedules, bf16=True))
    assert np.array_equal(a, b)

    big = np.ascontiguousarray(
        rng.integers(200, 5000, size=(5, 8)).astype(np.int32)
    )
    prob_big = PFSPProblem(lb="lb1", ub=0, p_times=big)
    t_big = P.PFSPDeviceTables(prob_big.lb1_data, prob_big.lb2_data)
    assert t_big.exact_bf16 is False
