"""Mesh-SPMD evaluator tests on the virtual 8-device CPU mesh: sharded
bounds must match the unsharded evaluators bit-exactly, the lb2 machine-pair
(mp) sharding must be transparent, and the in-step incumbent fold must
respect the valid-row count."""

import jax
import numpy as np
import pytest

from tpu_tree_search.parallel import mesh as M

# These tests need the virtual 8-device platform; a real-TPU run
# (TTS_TPU_TESTS=1) typically has fewer chips.
pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 devices (virtual CPU platform)"
)
from tpu_tree_search.problems import NQueensProblem, PFSPProblem
from tpu_tree_search.problems.pfsp import taillard as T


def _random_parents(jobs, B, depth, limit1, seed=0):
    rng = np.random.default_rng(seed)
    prmu = np.tile(np.arange(jobs, dtype=np.int32), (B, 1))
    for i in range(B):
        rng.shuffle(prmu[i])
    return {
        "depth": np.full((B,), depth, dtype=np.int32),
        "limit1": np.full((B,), limit1, dtype=np.int32),
        "prmu": prmu,
    }


def test_nqueens_mesh_matches_unsharded():
    prob = NQueensProblem(N=10)
    ev = M.MeshEvaluator(prob, M.make_mesh(8, mp=1))
    B = 16
    parents = {
        "depth": np.full((B,), 3, dtype=np.int32),
        "board": np.tile(np.arange(10, dtype=np.uint8), (B, 1)),
    }
    labels, _ = ev(parents, B, 0)
    ref = prob.make_device_evaluator()(parents, B, 0)
    assert np.array_equal(np.asarray(labels), np.asarray(ref))


@pytest.mark.parametrize("lb,mp", [("lb1", 1), ("lb1_d", 1), ("lb2", 1), ("lb2", 2), ("lb2", 4)])
def test_pfsp_mesh_matches_unsharded(lb, mp):
    ptm = T.reduced_instance(14, jobs=8, machines=5)
    prob = PFSPProblem(lb=lb, ub=0, p_times=ptm)
    ev = M.MeshEvaluator(prob, M.make_mesh(8, mp=mp))
    parents = _random_parents(8, 16, depth=3, limit1=2)
    bounds, nbest = ev(parents, 16, 10**9)
    ref = prob.make_device_evaluator()(parents, 16, 10**9)
    # Open child slots only (k > limit1): closed slots hold garbage by
    # contract, and the staged lb2 evaluator (TTS_LB2_STAGED=1) emits
    # different garbage there than the single-pass path.
    open_ = np.arange(8) > 2  # k > limit1 (the fixture's limit1=2)
    assert np.array_equal(
        np.asarray(bounds)[:, open_], np.asarray(ref)[:, open_]
    )
    assert nbest == 10**9  # no leaf children at depth 3 of 8


def test_pfsp_mesh_leaf_fold():
    ptm = T.reduced_instance(14, jobs=8, machines=5)
    prob = PFSPProblem(lb="lb1", ub=0, p_times=ptm)
    ev = M.MeshEvaluator(prob, M.make_mesh(8))
    parents = _random_parents(8, 16, depth=7, limit1=6)
    bounds, nbest = ev(parents, 16, 10**9)
    ref = np.asarray(prob.make_device_evaluator()(parents, 16, 10**9))
    assert nbest == ref[:, 7].min()


def test_pfsp_mesh_leaf_fold_masks_padding():
    """Padding rows beyond ``count`` must not leak into the incumbent fold,
    even when they are leaf-shaped clones with smaller makespans."""
    ptm = T.reduced_instance(14, jobs=8, machines=5)
    prob = PFSPProblem(lb="lb1", ub=0, p_times=ptm)
    ev = M.MeshEvaluator(prob, M.make_mesh(8))
    parents = _random_parents(8, 16, depth=7, limit1=6)
    ref = np.asarray(prob.make_device_evaluator()(parents, 16, 10**9))
    leaf_makespans = ref[:, 7]
    # Mask all but the first 8 rows; the fold over valid rows only.
    _, nbest = ev(parents, 8, 10**9)
    assert nbest == leaf_makespans[:8].min()
    # Sanity: some padding row would have changed the answer.
    if leaf_makespans[8:].min() < leaf_makespans[:8].min():
        assert nbest != leaf_makespans.min()
