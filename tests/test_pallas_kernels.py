"""Pallas kernels vs jnp/XLA oracles, bit-for-bit (interpret mode on CPU).

Mirrors the reference's cross-implementation validation strategy: the CUDA
device bounds are checked against the C host bounds by numeric agreement
(SURVEY.md §4.3); here the Pallas kernels are checked against the jnp
evaluators, which are themselves oracle-tested against the NumPy ports of
`c_bound_simple.c` (tests/test_bounds_oracle.py).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pytest

from tpu_tree_search.ops import nqueens_device, pallas_kernels, pfsp_device
from tpu_tree_search.problems import PFSPProblem
from tpu_tree_search.problems.pfsp import taillard


@pytest.mark.parametrize("g", [1, 3])
def test_nqueens_labels_match_oracle(g):
    rng = np.random.default_rng(7)
    N, B = 11, 700  # B not a tile multiple: exercises padding
    boards = np.stack([rng.permutation(N).astype(np.uint8) for _ in range(B)])
    depth = rng.integers(0, N + 1, B).astype(np.int32)
    oracle = nqueens_device.make_core(N, g)(jnp.asarray(boards), jnp.asarray(depth))
    got = pallas_kernels.nqueens_labels(
        jnp.asarray(boards), jnp.asarray(depth), N, g, interpret=True
    )
    assert np.array_equal(np.asarray(oracle), np.asarray(got))


@pytest.mark.parametrize("bf16", [False, True])
@pytest.mark.parametrize(
    "inst,jobs,machines",
    [(14, 20, 10), (1, 12, 5)],
)
def test_lb1_bounds_match_oracle(inst, jobs, machines, bf16):
    rng = np.random.default_rng(3)
    if jobs == 20:
        prob = PFSPProblem(inst=inst, lb="lb1", ub=1)
    else:
        ptm = taillard.reduced_instance(inst, jobs=jobs, machines=machines)
        prob = PFSPProblem(lb="lb1", ub=0, p_times=ptm)
    t = pfsp_device.PFSPDeviceTables(prob.lb1_data, prob.lb2_data)
    B = 300
    prmu = np.stack([rng.permutation(jobs).astype(np.int32) for _ in range(B)])
    limit1 = rng.integers(-1, jobs - 1, B).astype(np.int32)
    oracle = pfsp_device._lb1_chunk(
        jnp.asarray(prmu), jnp.asarray(limit1), t.ptm_t, t.min_heads, t.min_tails
    )
    got = pallas_kernels.pfsp_lb1_bounds(
        jnp.asarray(prmu), jnp.asarray(limit1), t.ptm_t, t.min_heads, t.min_tails,
        interpret=True, bf16=bf16,
    )
    assert np.array_equal(np.asarray(oracle), np.asarray(got))


@pytest.mark.parametrize("bf16", [False, True])
@pytest.mark.parametrize(
    "inst,jobs,machines",
    [(14, 20, 10), (1, 12, 5)],
)
def test_lb2_bounds_match_oracle(inst, jobs, machines, bf16):
    rng = np.random.default_rng(11)
    if jobs == 20:
        prob = PFSPProblem(inst=inst, lb="lb2", ub=1)
    else:
        ptm = taillard.reduced_instance(inst, jobs=jobs, machines=machines)
        prob = PFSPProblem(lb="lb2", ub=0, p_times=ptm)
    t = pfsp_device.PFSPDeviceTables(prob.lb1_data, prob.lb2_data)
    B = 200
    prmu = np.stack([rng.permutation(jobs).astype(np.int32) for _ in range(B)])
    limit1 = rng.integers(-1, jobs - 1, B).astype(np.int32)
    oracle = pfsp_device._lb2_chunk(
        jnp.asarray(prmu), jnp.asarray(limit1), t.ptm_t, t.min_heads,
        t.min_tails, t.pairs, t.lags, t.johnson_schedules,
    )
    got = pallas_kernels.pfsp_lb2_bounds(
        jnp.asarray(prmu), jnp.asarray(limit1), t, interpret=True, bf16=bf16
    )
    # Compare only open child slots (k > limit1): closed slots are garbage
    # by contract (never read by the host/engine).
    k = np.arange(jobs)[None, :]
    open_ = k >= limit1[:, None] + 1
    assert np.array_equal(
        np.asarray(oracle)[open_], np.asarray(got)[open_]
    )


def test_use_pallas_is_off_on_cpu(monkeypatch):
    import jax

    if jax.default_backend() == "tpu":
        pytest.skip("suite running on a real TPU backend (TTS_TPU_TESTS=1)")
    monkeypatch.delenv("TTS_PALLAS", raising=False)
    assert pallas_kernels.use_pallas() is False  # tests run on the CPU backend


def test_use_pallas_routes_per_device():
    """A CPU target device must never route to Pallas, whatever the default
    backend is (the round-2 dryrun failure mode)."""
    import jax

    cpus = jax.devices("cpu")
    assert pallas_kernels.use_pallas(cpus[0]) is False


@pytest.mark.parametrize(
    "lb,inst,jobs,machines",
    [
        ("lb1", 31, 50, 5),     # ta031 class
        ("lb1", 61, 100, 5),    # ta061 class
        ("lb1", 91, 200, 10),   # ta091 class
        ("lb1", 111, 500, 20),  # ta111 class — the reference's largest
        ("lb1_d", 31, 50, 5),
        ("lb2", 31, 50, 5),
        ("lb2", 61, 100, 5),
    ],
)
def test_large_instance_kernels_match_oracle(lb, inst, jobs, machines):
    """Large Taillard sizes (50-100 jobs) must stay on the Pallas path:
    _auto_tile shrinks the batch tile so the VMEM-resident pass still fits
    (the reference covers these by rebuilding with bigger params,
    `Taillard.chpl:29-52`). Full-size n with a small batch keeps interpret
    mode tractable on CPU."""
    rng = np.random.default_rng(5)
    prob = PFSPProblem(inst=inst, lb=lb, ub=1)
    assert prob.jobs == jobs and prob.machines == machines
    t = pfsp_device.PFSPDeviceTables(prob.lb1_data, prob.lb2_data)
    B = 24 if jobs <= 100 else 8  # interpret mode: keep 200/500-job cheap
    prmu = np.stack([rng.permutation(jobs).astype(np.int32) for _ in range(B)])
    limit1 = rng.integers(-1, jobs - 1, B).astype(np.int32)
    pd, ld = jnp.asarray(prmu), jnp.asarray(limit1)
    if lb == "lb1":
        oracle = pfsp_device._lb1_chunk(pd, ld, t.ptm_t, t.min_heads, t.min_tails)
        got = pallas_kernels.pfsp_lb1_bounds(
            pd, ld, t.ptm_t, t.min_heads, t.min_tails, interpret=True
        )
    elif lb == "lb1_d":
        oracle = pfsp_device._lb1_d_chunk(pd, ld, t.ptm_t, t.min_heads, t.min_tails)
        got = pallas_kernels.pfsp_lb1_d_bounds(
            pd, ld, t.ptm_t, t.min_heads, t.min_tails, interpret=True
        )
    else:
        oracle = pfsp_device._lb2_chunk(
            pd, ld, t.ptm_t, t.min_heads, t.min_tails,
            t.pairs, t.lags, t.johnson_schedules,
        )
        got = pallas_kernels.pfsp_lb2_bounds(pd, ld, t, interpret=True)
    k = np.arange(jobs)[None, :]
    open_ = k >= limit1[:, None] + 1
    assert np.array_equal(np.asarray(oracle)[open_], np.asarray(got)[open_])


def test_auto_tile_shrinks_for_large_instances():
    """The VMEM model must shrink tiles monotonically with job count and
    never go below the floor of 8."""
    at = pallas_kernels._auto_tile
    assert at(20, 10, 64) == 64          # ta014: default fits
    assert at(500, 20, 64) >= 8          # ta111: must shrink but stay valid
    assert at(500, 20, 64) < 64
    sizes = [at(n, 20, 256) for n in (20, 50, 100, 200, 500)]
    assert sizes == sorted(sizes, reverse=True)
    # Non-power-of-two overrides stay sublane-aligned and above the floor.
    for n in (20, 100, 500):
        t = at(n, 20, 100)
        assert t >= 8 and (t == 100 or t % 8 == 0)


@pytest.mark.parametrize("bf16", [False, True])
@pytest.mark.parametrize(
    "inst,jobs,machines",
    [(14, 20, 10), (1, 12, 5)],
)
def test_lb1_d_bounds_match_oracle(inst, jobs, machines, bf16):
    rng = np.random.default_rng(11)
    if jobs == 20:
        prob = PFSPProblem(inst=inst, lb="lb1_d", ub=1)
    else:
        ptm = taillard.reduced_instance(inst, jobs=jobs, machines=machines)
        prob = PFSPProblem(lb="lb1_d", ub=0, p_times=ptm)
    t = pfsp_device.PFSPDeviceTables(prob.lb1_data, prob.lb2_data)
    B = 300
    prmu = np.stack([rng.permutation(jobs).astype(np.int32) for _ in range(B)])
    limit1 = rng.integers(-1, jobs - 1, B).astype(np.int32)
    oracle = pfsp_device._lb1_d_chunk(
        jnp.asarray(prmu), jnp.asarray(limit1), t.ptm_t, t.min_heads, t.min_tails
    )
    got = pallas_kernels.pfsp_lb1_d_bounds(
        jnp.asarray(prmu), jnp.asarray(limit1), t.ptm_t, t.min_heads, t.min_tails,
        interpret=True, bf16=bf16,
    )
    assert np.array_equal(np.asarray(oracle), np.asarray(got))


def _random_nodes(rng, jobs, R, min_limit1=0):
    prmu = np.stack([rng.permutation(jobs).astype(np.int32) for _ in range(R)])
    limit1 = rng.integers(min_limit1, jobs - 1, R).astype(np.int32)
    return prmu, limit1


def test_lb2_self_chunk_matches_host_oracle():
    """The vectorized self bound (a node's OWN Johnson bound — the staged
    evaluator's second stage) must equal the NumPy host oracle
    (`lb2_bound`, c_bound_johnson.c:239-254) node by node."""
    from tpu_tree_search.problems.pfsp import bounds as B

    rng = np.random.default_rng(17)
    jobs = 8
    ptm = taillard.reduced_instance(14, jobs=jobs, machines=5)
    prob = PFSPProblem(lb="lb2", ub=0, p_times=ptm)
    t = pfsp_device.PFSPDeviceTables(prob.lb1_data, prob.lb2_data)
    prmu, limit1 = _random_nodes(rng, jobs, 64)
    got = np.asarray(pfsp_device._lb2_self_chunk(
        jnp.asarray(prmu), jnp.asarray(limit1), t.ptm_t, t.min_heads,
        t.min_tails, t.pairs, t.lags, t.johnson_schedules,
    ))
    for r in range(64):
        want = B.lb2_bound(
            prob.lb1_data, prob.lb2_data, prmu[r], int(limit1[r]), jobs, 10**9
        )
        assert got[r] == want, (r, got[r], want)


def test_lb2_self_kernel_matches_chunk_with_gating():
    """Pallas self kernel (interpret mode) vs the jnp self chunk on the
    active prefix; rows beyond n_active live in skipped tiles and are
    unconstrained."""
    rng = np.random.default_rng(23)
    prob = PFSPProblem(inst=14, lb="lb2", ub=1)
    jobs = prob.jobs
    t = pfsp_device.PFSPDeviceTables(prob.lb1_data, prob.lb2_data)
    R = 600  # not a tile multiple: exercises padding
    prmu, limit1 = _random_nodes(rng, jobs, R)
    oracle = np.asarray(pfsp_device._lb2_self_chunk(
        jnp.asarray(prmu), jnp.asarray(limit1), t.ptm_t, t.min_heads,
        t.min_tails, t.pairs, t.lags, t.johnson_schedules,
    ))
    for n_active in (R, 97):
        got = np.asarray(pallas_kernels.pfsp_lb2_self_bounds(
            jnp.asarray(prmu), jnp.asarray(limit1), n_active, t,
            interpret=True,
        ))
        assert np.array_equal(got[:n_active], oracle[:n_active])


def test_lb2_dominates_lb1_on_device_evaluators():
    """The staging invariant: the device lb2 child bounds are >= the device
    lb1 child bounds pointwise (every machine's lb1 term is the one-machine
    term of some Johnson pair), so skipping lb2 where lb1 >= best is exact."""
    rng = np.random.default_rng(29)
    for inst, jobs in ((14, 20), (1, 12)):
        if jobs == 20:
            prob = PFSPProblem(inst=inst, lb="lb2", ub=1)
        else:
            ptm = taillard.reduced_instance(inst, jobs=jobs, machines=5)
            prob = PFSPProblem(lb="lb2", ub=0, p_times=ptm)
        t = pfsp_device.PFSPDeviceTables(prob.lb1_data, prob.lb2_data)
        prmu, limit1 = _random_nodes(rng, jobs, 128)
        pd, ld = jnp.asarray(prmu), jnp.asarray(limit1)
        b1 = np.asarray(pfsp_device._lb1_chunk(
            pd, ld, t.ptm_t, t.min_heads, t.min_tails
        ))
        b2 = np.asarray(pfsp_device._lb2_chunk(
            pd, ld, t.ptm_t, t.min_heads, t.min_tails,
            t.pairs, t.lags, t.johnson_schedules,
        ))
        open_ = np.arange(jobs)[None, :] >= (limit1[:, None] + 1)
        assert np.all(b2[open_] >= b1[open_])


def test_lb1_family_demoted_to_jnp_by_default(monkeypatch):
    """The documented lb1 routing decision (docs/HW_VALIDATION.md): even on
    a TPU target the lb1/lb1_d evaluators default to the fused jnp path
    (measured ~7x the Pallas kernel in-kernel), and TTS_PALLAS=force is
    the only spelling that re-arms the kernels for the A/B."""
    prob = PFSPProblem(inst=14, lb="lb1", ub=1)
    t = pfsp_device.PFSPDeviceTables(prob.lb1_data, prob.lb2_data)
    rng = np.random.default_rng(47)
    prmu, limit1 = _random_nodes(rng, prob.jobs, 16)
    pd, ld = jnp.asarray(prmu), jnp.asarray(limit1)

    monkeypatch.delenv("TTS_PALLAS", raising=False)
    monkeypatch.delenv("TTS_PALLAS_INTERPRET", raising=False)
    monkeypatch.setattr(pallas_kernels, "use_pallas", lambda d=None: True)
    monkeypatch.setattr(
        pallas_kernels, "pfsp_lb1_bounds",
        lambda *a, **k: pytest.fail("lb1 kernel dispatched without force"),
    )
    monkeypatch.setattr(
        pallas_kernels, "pfsp_lb1_d_bounds",
        lambda *a, **k: pytest.fail("lb1_d kernel dispatched without force"),
    )
    oracle = np.asarray(pfsp_device._lb1_chunk(
        pd, ld, t.ptm_t, t.min_heads, t.min_tails
    ))
    got = np.asarray(pfsp_device.lb1_bounds(pd, ld, t))
    assert np.array_equal(got, oracle)
    assert pfsp_device.lb1_d_bounds(pd, ld, t) is not None

    sentinel = object()
    monkeypatch.setenv("TTS_PALLAS", "force")
    monkeypatch.setattr(
        pallas_kernels, "pfsp_lb1_bounds", lambda *a, **k: sentinel
    )
    assert pfsp_device.lb1_bounds(pd, ld, t) is sentinel
    # The force spelling is part of the routing token: flipping it must
    # rebuild cached programs, never reuse a stale one.
    tok_forced = pfsp_device.routing_cache_token(prob)
    monkeypatch.setenv("TTS_PALLAS", "1")
    assert pfsp_device.routing_cache_token(prob) != tok_forced


def test_lb2_family_kill_switch_spares_lb1(monkeypatch):
    """TTS_PALLAS_LB2=0 (bench.py's fallback when only the lb2-family probe
    fails) must route the lb2 child/self kernels AND auto-staging to the
    jnp path while the (force-armed) lb1 family keeps its Pallas route —
    an lb2 compile failure may never cost the lb1 kernel A/B (VERDICT r4
    weak #6). The lb1 family is demoted to jnp by DEFAULT now
    (docs/HW_VALIDATION.md decision record); TTS_PALLAS=force re-arms it,
    which is what this test pins alongside the kill switch."""
    prob = PFSPProblem(inst=14, lb="lb2", ub=1)
    t = pfsp_device.PFSPDeviceTables(prob.lb1_data, prob.lb2_data)
    rng = np.random.default_rng(43)
    prmu, limit1 = _random_nodes(rng, prob.jobs, 16)
    pd, ld = jnp.asarray(prmu), jnp.asarray(limit1)

    monkeypatch.setenv("TTS_PALLAS_LB2", "0")
    monkeypatch.setenv("TTS_PALLAS", "force")
    monkeypatch.setattr(pallas_kernels, "use_pallas", lambda d=None: True)
    monkeypatch.setattr(
        pallas_kernels, "pfsp_lb2_bounds",
        lambda *a, **k: pytest.fail("lb2 kernel dispatched despite =0"),
    )
    monkeypatch.setattr(
        pallas_kernels, "pfsp_lb2_self_bounds",
        lambda *a, **k: pytest.fail("lb2 self kernel dispatched despite =0"),
    )
    sentinel = object()
    monkeypatch.setattr(
        pallas_kernels, "pfsp_lb1_bounds", lambda *a, **k: sentinel
    )
    oracle = np.asarray(pfsp_device._lb2_chunk(
        pd, ld, t.ptm_t, t.min_heads, t.min_tails,
        t.pairs, t.lags, t.johnson_schedules,
    ))
    got = np.asarray(pfsp_device.lb2_bounds(pd, ld, t))  # jnp path
    open_ = np.arange(prob.jobs)[None, :] >= (limit1[:, None] + 1)
    assert np.array_equal(got[open_], oracle[open_])
    assert pfsp_device.lb2_self_bounds(pd, jnp.maximum(ld, 0), 16, t) is not None
    assert not pfsp_device.lb2_staged_enabled(None, prob.jobs)  # auto -> off
    assert pfsp_device.lb1_bounds(pd, ld, t) is sentinel  # lb1 unaffected


def test_lb2_self_mp_shard_maxes_combine_to_full():
    """The mp-sharded self bound's per-shard pieces (sliced ordered tables
    through the Pallas kernel, interpret mode) must pmax-combine to exactly
    the full-pair self bound — including a pair count that needs padding
    (max over duplicated pair 0 is idempotent)."""
    rng = np.random.default_rng(37)
    jobs = 8
    ptm = taillard.reduced_instance(14, jobs=jobs, machines=5)
    prob = PFSPProblem(lb="lb2", ub=0, p_times=ptm)
    t = pfsp_device.PFSPDeviceTables(prob.lb1_data, prob.lb2_data)
    R = 64
    prmu, limit1 = _random_nodes(rng, jobs, R)
    pd, ld = jnp.asarray(prmu), jnp.asarray(limit1)
    full = np.asarray(pfsp_device._lb2_self_chunk(
        pd, ld, t.ptm_t, t.min_heads, t.min_tails,
        t.pairs, t.lags, t.johnson_schedules,
    ))
    for mp_size in (2, 3):  # P=10 pairs: 3 forces padding to 12
        P_pad = -(-t.pairs.shape[0] // mp_size) * mp_size
        P_local = P_pad // mp_size
        ordered = t.johnson_ordered_mp(mp_size)
        parts = []
        for shard in range(mp_size):
            sliced = pfsp_device._OrderedSlice(
                ordered, shard * P_local, P_local
            )
            parts.append(np.asarray(pallas_kernels.pfsp_lb2_self_bounds_tables(
                pd, ld, R, t.ptm_t, sliced, interpret=True,
                bf16=t.exact_bf16,
            )))
        assert np.array_equal(np.maximum.reduce(parts), full), mp_size


def test_lb2_staged_mp_matches_full_inside_shard_map():
    """lb2_bounds_staged with mp_axis set, run inside a REAL shard_map over
    an mp-only mesh (2 CPU devices): the compaction runs per replica, the
    self bound slices its pair block per shard and pmax-combines — results
    must equal the full child evaluator on every candidate slot, on every
    replica."""
    import jax
    from jax.sharding import Mesh, PartitionSpec as P

    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    rng = np.random.default_rng(41)
    jobs = 8
    ptm = taillard.reduced_instance(14, jobs=jobs, machines=5)
    prob = PFSPProblem(lb="lb2", ub=0, p_times=ptm)
    t = pfsp_device.PFSPDeviceTables(prob.lb1_data, prob.lb2_data)
    B = 32
    prmu, limit1 = _random_nodes(rng, jobs, B)
    pd, ld = jnp.asarray(prmu), jnp.asarray(limit1)
    full = np.asarray(pfsp_device._lb2_chunk(
        pd, ld, t.ptm_t, t.min_heads, t.min_tails,
        t.pairs, t.lags, t.johnson_schedules,
    ))
    open_ = np.arange(jobs)[None, :] >= (limit1[:, None] + 1)
    leaf = open_ & ((limit1[:, None] + 2) == jobs)
    cand = open_ & ~leaf & (rng.random((B, jobs)) < 0.5)
    mesh = Mesh(np.asarray(jax.devices()[:2]), ("mp",))

    def body(pd, ld, cd):
        return pfsp_device.lb2_bounds_staged(
            pd, ld, cd, t, mp_axis="mp", mp_size=2
        )[None]

    from tpu_tree_search.utils import jax_compat

    got = np.asarray(jax.jit(jax_compat.shard_map(
        body, mesh=mesh,
        in_specs=(P(), P(), P()), out_specs=P("mp"),
    ))(pd, ld, jnp.asarray(cand)))
    # Every mp replica computed identical full-pair bounds (lockstep).
    assert np.array_equal(got[0][cand], full[cand])
    assert np.array_equal(got[1][cand], full[cand])


def test_lb2_staged_bounds_match_full_on_candidates():
    """lb2_bounds_staged (compaction + self bound + scatter) equals the full
    child evaluator everywhere the candidate mask is set."""
    rng = np.random.default_rng(31)
    prob = PFSPProblem(inst=14, lb="lb2", ub=1)
    jobs = prob.jobs
    t = pfsp_device.PFSPDeviceTables(prob.lb1_data, prob.lb2_data)
    B = 48
    prmu, limit1 = _random_nodes(rng, jobs, B)
    pd, ld = jnp.asarray(prmu), jnp.asarray(limit1)
    full = np.asarray(pfsp_device._lb2_chunk(
        pd, ld, t.ptm_t, t.min_heads, t.min_tails,
        t.pairs, t.lags, t.johnson_schedules,
    ))
    open_ = np.arange(jobs)[None, :] >= (limit1[:, None] + 1)
    leaf = open_ & ((limit1[:, None] + 2) == jobs)
    cand = open_ & ~leaf & (rng.random((B, jobs)) < 0.4)
    got = np.asarray(pfsp_device.lb2_bounds_staged(
        pd, ld, jnp.asarray(cand), t
    ))
    assert np.array_equal(got[cand], full[cand])
