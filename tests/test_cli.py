"""CLI surface tests: flag parsing/validation, banner + JSON + stats-file
output, golden counts through ``main()`` — the reference's per-main
`check_parameters`/`print_results` behavior (`pfsp_chpl.chpl:42-77`)
centralized in one program."""

from __future__ import annotations

import json

import pytest

from tpu_tree_search import cli


def _last_json(out: str) -> dict:
    return json.loads(out.strip().splitlines()[-1])


def test_seq_json_golden(capsys):
    assert cli.main(["nqueens", "--N", "8", "--json"]) == 0
    rec = _last_json(capsys.readouterr().out)
    assert (rec["explored_tree"], rec["explored_sol"]) == (2056, 92)
    assert rec["tier"] == "seq"


def test_device_tier_banner_and_stats(tmp_path, capsys):
    stats = tmp_path / "stats.dat"
    assert cli.main([
        "nqueens", "--N", "8", "--tier", "device", "--m", "5", "--M", "64",
        "--stats-file", str(stats),
    ]) == 0
    out = capsys.readouterr().out
    assert "Single-device TPU tree search" in out
    assert "Size of the explored tree: 2056" in out
    rec = json.loads(stats.read_text().strip())
    assert rec["explored_sol"] == 92


def test_pfsp_banner_reports_makespan(capsys):
    # Full Taillard searches take minutes on CPU; a --max-steps cutoff still
    # exercises the whole banner path (settings, interruption notice, and
    # the ub=1 makespan line).
    assert cli.main([
        "pfsp", "--inst", "1", "--lb", "lb1", "--tier", "device",
        "--m", "5", "--M", "512", "--K", "2", "--max-steps", "2",
    ]) == 0
    out = capsys.readouterr().out
    assert "Taillard's instance: ta001" in out
    assert "Exploration interrupted" in out
    assert "Optimal makespan: 1278 (not improved)" in out


@pytest.mark.parametrize("argv,msg", [
    (["nqueens", "--tier", "mesh", "--engine", "offload"], "resident-only"),
    (["nqueens", "--tier", "seq", "--perc", "0.3"], "--perc only applies"),
    (["nqueens", "--tier", "seq", "--hosts", "2"], "only apply to --tier dist"),
    (["nqueens", "--tier", "dist", "--hosts", "0"], "--hosts must be >= 1"),
    (["nqueens", "--tier", "seq", "--mp", "2"], "--mp only applies"),
    (["pfsp", "--tier", "mesh", "--lb", "lb1", "--mp", "2"], "lb2 Johnson"),
    (["nqueens", "--tier", "dist", "--distributed", "--hosts", "2"],
     "mutually exclusive"),
    (["nqueens", "--tier", "multi", "--perc", "1.5"], "in (0, 1]"),
    (["nqueens", "--tier", "multi", "--perc", "0"], "in (0, 1]"),
    (["nqueens", "--tier", "multi", "--perc", "-0.25"], "in (0, 1]"),
    (["nqueens", "--tier", "dist", "--coordinator", "localhost:1"],
     "require --distributed"),
    (["nqueens", "--tier", "dist", "--host-id", "0"], "require --distributed"),
    (["nqueens", "--tier", "seq", "--steal-interval", "0.1"],
     "only applies to --tier dist"),
    (["nqueens", "--tier", "dist", "--steal-interval", "-1"], "must be > 0"),
])
def test_flag_validation(argv, msg, capsys):
    with pytest.raises(SystemExit) as e:
        cli.main(argv)
    assert e.value.code == 2
    assert msg in capsys.readouterr().err


@pytest.mark.parametrize("M,name,tier,engine,backend,expect", [
    (7777, "pfsp", "device", "resident", "tpu", 7777),  # explicit wins
    (None, "pfsp", "device", "resident", "tpu", 1024),  # measured default
    (None, "pfsp", "device", "resident", "cpu", 50000),  # unmeasured backend
    # The GPU row: the reference's published ~50k-node offload chunk
    # (arXiv 2012.09511 §IV) rounded DOWN to a multiple of 8 (50000 % 8
    # == 2 would refuse the megakernel/tiled-compaction alignment gates).
    (None, "pfsp", "device", "resident", "gpu", 49152),
    (None, "pfsp", "device", "offload", "gpu", 50000),  # non-candidate
    (None, "nqueens", "device", "resident", "gpu", 50000),  # wide frontier
    (None, "pfsp", "device", "offload", "tpu", 50000),  # per-chunk round trip
    (None, "pfsp", "mesh", "resident", "tpu", 50000),   # sharded: per shard
    (None, "nqueens", "device", "resident", "tpu", 50000),  # wide frontier
])
def test_resolve_chunk_size(M, name, tier, engine, backend, expect):
    """--M defaults come from the round-5 on-chip tuning
    (docs/HW_VALIDATION.md); explicit values, the offload engine, and
    unmeasured combinations keep the reference's 50000 (`util.chpl`)."""
    assert cli.resolve_chunk_size(M, name, tier, engine, backend) == expect
    assert cli.resolve_chunk_size(None, "pfsp", "device", "resident",
                                  "gpu") % 8 == 0


def test_resolve_chunk_size_backend_default_tracks_kernel_knob(monkeypatch):
    """With no explicit backend the candidate row resolves through
    ops/backend.policy_backend: TTS_KERNEL_BACKEND=gpu on this CPU host
    must pick the GPU chunk row (CI routes like a GPU host), while the
    unset knob keeps the host platform's row."""
    import jax

    if jax.default_backend() == "tpu":
        pytest.skip("suite running on a real TPU backend (TTS_TPU_TESTS=1)")
    monkeypatch.delenv("TTS_KERNEL_BACKEND", raising=False)
    assert cli.resolve_chunk_size(None, "pfsp", "device", "resident") == 50000
    monkeypatch.setenv("TTS_KERNEL_BACKEND", "gpu")
    assert cli.resolve_chunk_size(None, "pfsp", "device", "resident") == 49152
    # Forced tpu off-TPU stays jnp-routed (policy_backend returns the
    # physical platform), so the chunk row must NOT flip to 1024.
    monkeypatch.setenv("TTS_KERNEL_BACKEND", "tpu")
    assert cli.resolve_chunk_size(None, "pfsp", "device", "resident") == 50000


def test_resolve_chunk_size_non_candidates_skip_backend_probe():
    """--tier seq (and every non-candidate) must not import/initialize jax
    just to compute a chunk size it discards."""
    import builtins
    from unittest import mock

    real_import = builtins.__import__

    def guarded(name, *a, **kw):
        assert name != "jax", "non-candidate resolved the backend"
        return real_import(name, *a, **kw)

    with mock.patch.object(builtins, "__import__", side_effect=guarded):
        assert cli.resolve_chunk_size(None, "nqueens", "seq", "resident") == 50000
        assert cli.resolve_chunk_size(None, "pfsp", "device", "offload") == 50000


def test_compact_flag_pins_env_and_is_recorded(capsys, monkeypatch):
    """--compact must pin TTS_COMPACT for the run (restoring afterwards —
    two main() calls in one process must not leak the pin) and the JSON
    record must name the active mode (so a stats line proves which
    compaction ran); tiers whose engine never compacts carry no key and
    reject the flag."""
    import os

    monkeypatch.delenv("TTS_COMPACT", raising=False)
    cli.main(["nqueens", "--N", "8", "--tier", "device", "--M", "64",
              "--compact", "sort", "--json"])
    rec = _last_json(capsys.readouterr().out)
    assert rec["compact"] == "sort"
    assert rec["explored_sol"] == 92  # N=8 golden
    assert "TTS_COMPACT" not in os.environ  # pin restored, not leaked

    cli.main(["nqueens", "--N", "8", "--tier", "device", "--M", "64",
              "--json"])
    rec2 = _last_json(capsys.readouterr().out)
    # Default knob is auto; the record carries the RESOLVED path (dense for
    # N-Queens — ops/compaction.py policy), not the prior run's pin.
    assert rec2["compact"] == "dense" and rec2["compact_auto"] is True

    # Offload/seq runs never compact: no flag, no key.
    with pytest.raises(SystemExit) as e:
        cli.main(["nqueens", "--N", "8", "--tier", "device",
                  "--engine", "offload", "--compact", "sort"])
    assert e.value.code == 2
    cli.main(["nqueens", "--N", "8", "--tier", "device",
              "--engine", "offload", "--M", "64", "--json"])
    assert "compact" not in _last_json(capsys.readouterr().out)
