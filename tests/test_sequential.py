"""Golden-count tests for the sequential engine (the correctness anchor,
SURVEY.md §4.1-4.2).

N-Queens solution counts are classical literature values; exploredTree values
are self-anchored goldens (recorded from this engine, then frozen — any
change is a semantic regression). PFSP goldens use small reduced instances
plus the ub=1 invariant on real instances where feasible.
"""

import numpy as np
import pytest

from tpu_tree_search.engine import sequential_search
from tpu_tree_search.problems import NQueensProblem, PFSPProblem
from tpu_tree_search.problems.pfsp import bounds as B
from tpu_tree_search.problems.pfsp import taillard as T

# Classical total-solution counts for N-Queens.
QUEENS_SOLUTIONS = {4: 2, 5: 10, 6: 4, 7: 40, 8: 92, 9: 352, 10: 724}


@pytest.mark.parametrize("n", [4, 5, 6, 7, 8])
def test_nqueens_solution_counts(n):
    res = sequential_search(NQueensProblem(N=n))
    assert res.explored_sol == QUEENS_SOLUTIONS[n]


def test_nqueens_g_does_not_change_counts():
    r1 = sequential_search(NQueensProblem(N=7, g=1))
    r3 = sequential_search(NQueensProblem(N=7, g=3))
    assert (r1.explored_tree, r1.explored_sol) == (r3.explored_tree, r3.explored_sol)


# Self-anchored goldens: frozen after first recording (see module docstring).
NQUEENS_TREE_GOLDEN = {}  # filled by test generation script; asserted if present


def _brute_force_pfsp(ptm):
    """Exhaustive optimum by enumerating all permutations (tiny instances)."""
    from itertools import permutations

    d = B.make_lb1(ptm)
    n = ptm.shape[1]
    return min(B.eval_solution(d, np.array(p, dtype=np.int32)) for p in permutations(range(n)))


@pytest.mark.parametrize("lb", ["lb1", "lb1_d", "lb2"])
def test_pfsp_reduced_finds_bruteforce_optimum(lb):
    ptm = T.reduced_instance(14, jobs=7, machines=5)
    prob = PFSPProblem(lb=lb, ub=0, p_times=ptm)
    res = sequential_search(prob)
    assert res.best == _brute_force_pfsp(ptm)


@pytest.mark.parametrize("lb", ["lb1", "lb1_d", "lb2"])
def test_pfsp_reduced_ub_seeded_keeps_optimum(lb):
    """Seeding best with the optimum must terminate with the same value and
    count at least one solution path decision consistently (mirrors the
    reference's ub=1 invariant, `pfsp_chpl.chpl:40,66-77`)."""
    ptm = T.reduced_instance(14, jobs=7, machines=5)
    opt = _brute_force_pfsp(ptm)
    prob = PFSPProblem(lb=lb, ub=0, p_times=ptm)
    res = sequential_search(prob, initial_best=opt)
    assert res.best == opt


def test_pfsp_lb_variants_agree_on_optimum():
    ptm = T.reduced_instance(21, jobs=6, machines=8)
    results = {
        lb: sequential_search(PFSPProblem(lb=lb, ub=0, p_times=ptm)).best
        for lb in ("lb1", "lb1_d", "lb2")
    }
    assert len(set(results.values())) == 1
    # tree sizes differ between bounds (lb1_d is weaker; lb2 stronger)
