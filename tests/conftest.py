"""Test configuration.

Tests run on a virtual 8-device CPU platform so the multi-device and
multi-host tiers are exercised without TPU hardware (SURVEY.md §4's
fake-multi-host strategy; cf. the reference's oversubscribed-locale smoke
testing via CHPL_COMM_SUBSTRATE=udp, `g5k_dist_multigpu_nvidia.sh:33`).

The image's sitecustomize registers the TPU backend at interpreter startup
and pins the platform through jax's config (not just the environment), so
overriding the environment here is not enough — the config must be updated
too, before any backend initializes.
"""

import os

# TTS_TPU_TESTS=1 skips the CPU pin so the hardware gate
# (tests/test_tpu_smoke.py) can compile the Pallas kernels on a real chip
# (`TTS_TPU_TESTS=1 pytest tests/test_tpu_smoke.py`). The rest of the suite
# is CPU-oriented: tests needing the virtual 8-device platform skip
# themselves when fewer devices exist.
if os.environ.get("TTS_TPU_TESTS", "0") != "1":
    os.environ["PALLAS_AXON_POOL_IPS"] = ""  # disable TPU plugin registration
    os.environ["JAX_PLATFORMS"] = "cpu"
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()

    import jax

    jax.config.update("jax_platforms", "cpu")
