"""Taillard generator parity.

Golden checksums were produced by compiling the reference C generator
(`baselines/pfsp/lib/c_taillard.c`) and dumping (jobs, machines, sum, first
rows); the LCG's float32 division makes these bit-exact invariants.
"""

import numpy as np

from tpu_tree_search.problems.pfsp import taillard as T

# inst -> (jobs, machines, total_sum, first 10 flat values)
GOLDEN = {
    1: (20, 5, 5153, [54, 83, 15, 71, 77, 36, 53, 38, 27, 87]),
    14: (20, 10, 8930, [94, 43, 6, 47, 45, 51, 73, 49, 31, 58]),
    21: (20, 20, 20273, [50, 90, 39, 34, 66, 81, 27, 48, 46, 68]),
    31: (50, 5, 12077, [75, 87, 13, 11, 41, 43, 93, 69, 80, 13]),
    114: (500, 20, 500754, [3, 94, 39, 10, 2, 66, 26, 6, 83, 12]),
}


def test_sizes_and_checksums():
    for inst, (jobs, machines, total, head) in GOLDEN.items():
        ptm = T.processing_times(inst)
        assert T.nb_jobs(inst) == jobs
        assert T.nb_machines(inst) == machines
        assert ptm.shape == (machines, jobs)
        assert int(ptm.sum()) == total
        assert list(ptm.ravel()[:10]) == head
        assert ptm.min() >= 1 and ptm.max() <= 99


def test_best_ub_table():
    assert T.best_ub(14) == 1377
    assert T.best_ub(1) == 1278
    assert T.best_ub(21) == 2297
    assert T.best_ub(30) == 2178
    assert T.best_ub(120) == 26457
    assert len(T.OPTIMAL_MAKESPANS) == 120 and len(T.TIME_SEEDS) == 120


def test_reduced_instance():
    r = T.reduced_instance(14, jobs=8, machines=5)
    full = T.processing_times(14)
    assert r.shape == (5, 8)
    assert np.array_equal(r, full[:5, :8])
