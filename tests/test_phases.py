"""Per-phase cycle-clock profiler (`tpu_tree_search/obs/phases.py`,
docs/OBSERVABILITY.md leg 7): byte-identical jaxprs when off, the exact
phase-sum == total telescoping identity, bit-identical search results
armed vs not, cross-tier harvest parity, guard interaction, and the
`tts report` / `tts profile` decomposition table."""

from __future__ import annotations

import json

import pytest

from tpu_tree_search import cli
from tpu_tree_search.obs import phases, report
from tpu_tree_search.problems import NQueensProblem


def _cycle_sum(pp: dict) -> int:
    return sum(pp[s] for s in phases.CYCLE_SLOTS)


# -- zero-cost disabled path (routed through the contract registry) --------
# The byte-identity, block-leaf, and cache-key claims are Contracts
# (obs/phases.py, engine/resident.py) checked over the whole knob matrix
# by `tts check`; these tests pin the same registry entries on the
# historical cell.


def test_disabled_jaxpr_identical_and_clock_free():
    from tpu_tree_search.analysis import contracts, program_audit

    program_audit.load_contracts()
    art = program_audit.variant_artifact(
        "nqueens", labels=["off", "phase0", "phase1", "phase1-obs1"]
    )
    # Off builds are byte-identical: the phase block is compiled out, not
    # branched — exactly the counter-block contract (tests/test_obs.py).
    # The armed build carries exactly one extra output leaf (the phase
    # block); with device counters too, one more (order: ..., ctr, ph).
    assert contracts.run_one("phaseprof-off-identity", art) == []
    assert contracts.run_one("phaseprof-block-leaf", art) == []
    assert art.outvars("off") == 7


def test_program_cache_keys_on_phaseprof():
    from tpu_tree_search.analysis import contracts, program_audit

    program_audit.load_contracts()
    art = program_audit.cache_key_artifact("nqueens")
    a, b = art.distinct["TTS_PHASEPROF"]
    assert b.phaseprof and not a.phaseprof
    assert contracts.run_one("program-cache-key-sound", art) == []


# -- armed semantics: bit-identity + the telescoping identity --------------


def test_resident_bit_identity_and_phase_sum(monkeypatch):
    from tpu_tree_search.engine.resident import resident_search

    monkeypatch.delenv("TTS_PHASEPROF", raising=False)
    res_off = resident_search(NQueensProblem(N=9), m=5, M=128)
    monkeypatch.setenv("TTS_PHASEPROF", "1")
    res_on = resident_search(NQueensProblem(N=9), m=5, M=128)
    # Clock reads feed only the phase block: search results stay
    # bit-identical armed vs not.
    assert (res_on.explored_tree, res_on.explored_sol, res_on.best) == \
        (res_off.explored_tree, res_off.explored_sol, res_off.best)
    assert res_off.phase_profile is None
    pp = res_on.phase_profile
    assert pp is not None and pp["total"] > 0
    # The stated consistency bound: within a cycle the same clock readings
    # bound adjacent phases, so the in-cycle slots telescope to `total`
    # EXACTLY (uint32 wrap arithmetic is exact; host merge uses int64+).
    assert _cycle_sum(pp) == pp["total"]
    # Sanity: measured on-device cycle time fits inside the run's wall
    # clock (single device — no aggregation slack needed).
    assert pp["total"] < res_on.elapsed * 1e9
    # The armed result also rides the obs payload for stats lines.
    assert res_on.obs["device_phases"] == pp


def test_mesh_phase_parity(monkeypatch):
    import jax

    from tpu_tree_search.parallel.resident_mesh import mesh_resident_search

    if len(jax.devices()) < 4:
        pytest.skip("needs >= 4 devices")
    monkeypatch.setenv("TTS_PHASEPROF", "1")
    res = mesh_resident_search(NQueensProblem(N=8), m=5, M=64, D=4)
    # Counting invariance is untouched by the clocks.
    assert (res.explored_tree, res.explored_sol) == (2056, 92)
    pp = res.phase_profile
    assert pp is not None
    # Telescoping holds summed across shards too (it holds per shard and
    # the merge is a plain sum).
    assert _cycle_sum(pp) == pp["total"] > 0
    # The mesh tiers charge the pmin fold + ppermute diffusion to
    # `balance` — present (>= 0; N=8 on 4 shards always runs rounds).
    assert pp["balance"] >= 0 and pp["loop"] > 0


def test_dist_mesh_phase_parity(monkeypatch):
    import jax

    from tpu_tree_search.parallel.dist_mesh import dist_mesh_search

    if len(jax.devices()) < 4:
        pytest.skip("needs >= 4 devices")
    monkeypatch.setenv("TTS_PHASEPROF", "1")
    res = dist_mesh_search(NQueensProblem(N=8), m=5, M=64, D=2, num_hosts=2)
    assert (res.explored_tree, res.explored_sol) == (2056, 92)
    pp = res.phase_profile
    assert pp is not None
    assert _cycle_sum(pp) == pp["total"] > 0


def test_guard_green_while_armed(monkeypatch):
    from tpu_tree_search.engine.resident import resident_search

    monkeypatch.setenv("TTS_PHASEPROF", "1")
    monkeypatch.setenv("TTS_GUARD", "1")
    # The armed variant harvests at the same dispatch boundaries: zero new
    # transfers, zero steady-state recompiles — GuardViolation would raise.
    res = resident_search(NQueensProblem(N=9), m=5, M=128)
    assert (res.explored_tree, res.explored_sol) == (8393, 352)


# -- merge/share helpers ---------------------------------------------------


def test_merge_host_and_shares():
    import numpy as np

    blk = np.zeros((2, phases.NSLOTS + 1), np.uint32)
    blk[0, phases.IDX["eval"]] = 100
    blk[0, phases.IDX["total"]] = 150
    blk[1, phases.IDX["eval"]] = 50
    blk[1, phases.IDX["total"]] = 150
    blk[:, phases.TPREV] = 12345  # carried clock reading: never merged
    tot = phases.merge_host(None, blk)
    assert tot["eval"] == 150 and tot["total"] == 300
    assert "tprev" not in tot and len(tot) == phases.NSLOTS
    tot = phases.merge_host(tot, blk[:1])
    assert tot["eval"] == 250
    sh = phases.shares(tot)
    assert sh["eval"] == pytest.approx(250 / 450)
    name, share = phases.dominant_phase(tot)
    assert name == "eval"
    assert phases.dominant_phase({}) is None
    assert phases.dominant_phase(None) is None


# -- report/CLI surfaces ---------------------------------------------------


def _phase_counter_event(ns: dict) -> dict:
    return {"name": "device_phases", "cat": "metrics", "ph": "C",
            "ts": 1.0, "pid": 0, "tid": 0, "args": ns}


def test_report_phase_table_golden(capsys):
    evts = [
        _phase_counter_event({"pop": 100, "eval": 200, "compact": 410,
                              "push": 250, "overflow": 40, "balance": 5,
                              "loop": 30, "total": 1000}),
        _phase_counter_event({"pop": 0, "eval": 0, "compact": 0,
                              "push": 0, "overflow": 0, "balance": 0,
                              "loop": 0, "total": 0}),
    ]
    summary = report.summarize(evts)
    pd = summary["phase_decomp"]
    assert pd["ns"]["compact"] == 410 and pd["ns"]["total"] == 1000
    assert pd["dominant"] == "compact"
    assert pd["dominant_share"] == pytest.approx(0.41)
    text = report.render(summary)
    # Golden lines of the decomposition table.
    assert "phase decomposition (on-device cycle clocks, ns):" in text
    assert "next structural cost: compaction, 41% of cycle" in text
    assert "bound evaluation" in text and "fused prune+push" in text
    # No device_phases events -> no table, no crash.
    empty = report.summarize([])
    assert empty["phase_decomp"] is None
    assert "next structural cost" not in report.render(empty)


def test_cli_profile_subcommand(monkeypatch, capsys):
    monkeypatch.delenv("TTS_PHASEPROF", raising=False)
    rc = cli.main(["profile", "nqueens", "--N", "8", "--tier", "device",
                   "--M", "64", "--m", "5", "--json"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "phase decomposition (on-device cycle clocks, ns):" in out
    assert "next structural cost:" in out
    rec = json.loads(out.strip().splitlines()[-1])
    assert rec["obs"]["device_phases"]["total"] > 0
    # The pin is restored: a later build in this process is unarmed.
    import os

    assert os.environ.get("TTS_PHASEPROF") is None


def test_cli_profile_requires_run_command(capsys):
    with pytest.raises(SystemExit):
        cli.main(["profile"])
    with pytest.raises(SystemExit):
        cli.main(["profile", "report", "x.json"])


def test_cli_phase_profile_flag_rejected_off_resident():
    with pytest.raises(SystemExit):
        cli.main(["nqueens", "--tier", "seq", "--phase-profile"])
    with pytest.raises(SystemExit):
        cli.main(["nqueens", "--tier", "device", "--engine", "offload",
                  "--phase-profile"])


def test_xla_trace_window_brackets_steady_state(tmp_path, monkeypatch):
    calls = []

    class _FakeProfiler:
        @staticmethod
        def start_trace(d):
            calls.append(("start", d))

        @staticmethod
        def stop_trace():
            calls.append(("stop", None))

    import jax

    monkeypatch.setattr(jax, "profiler", _FakeProfiler)
    monkeypatch.setenv("TTS_XLA_TRACE", str(tmp_path / "xt"))
    win = phases.XlaTraceWindow("resident")
    win.on_dispatch(1)  # first dispatch = compile; window stays closed
    assert calls == []
    win.on_dispatch(2)
    assert calls == [("start", str(tmp_path / "xt"))]
    # A second window (dist_mesh virtual-host thread) is a no-op while
    # the first is active — the jax profiler is process-global.
    win2 = phases.XlaTraceWindow("dist_mesh")
    win2.on_dispatch(5)
    win2.close()
    assert calls == [("start", str(tmp_path / "xt"))]
    win.on_dispatch(3)  # already started: no re-arm
    win.close()
    assert calls[-1] == ("stop", None)
    # Released: a later run can open a new window.
    win3 = phases.XlaTraceWindow("resident")
    assert win3._owner
    win3.close()


def test_cli_xla_trace_end_to_end(tmp_path, monkeypatch):
    import os

    out_dir = tmp_path / "xprof"
    rc = cli.main(["nqueens", "--N", "9", "--tier", "device", "--M", "128",
                   "--m", "5", "--K", "4", "--xla-trace", str(out_dir)])
    assert rc == 0
    # The steady-state capture landed (jax writes
    # plugins/profile/<ts>/*.xplane.pb under the directory).
    files = [f for _, _, fs in os.walk(out_dir) for f in fs]
    assert files, "no XLA trace artifacts written"
    assert os.environ.get("TTS_XLA_TRACE") is None


def test_flightrec_snapshot_names_dominant_phase(monkeypatch):
    from tpu_tree_search.obs import flightrec
    from tpu_tree_search.obs.live import format_snapshot

    monkeypatch.setenv("TTS_FLIGHTREC", "1")
    rec = flightrec.FlightRecorder(snapshot_period_us=0.0)
    rec.heartbeat("resident", seq=1, cycles=4, size=10, best=3, tree=100,
                  sol=1, phases={"pop": 10, "eval": 20, "compact": 50,
                                 "push": 15, "overflow": 5, "balance": 0,
                                 "loop": 2, "total": 100})
    snap = rec.latest()
    assert snap["dominant_phase"] == "compact"
    assert snap["dominant_phase_share"] == pytest.approx(0.5)
    assert snap["phases"]["compact"] == 50
    # /state (the post-mortem payload) carries the split per worker.
    st = rec.state()
    assert st["last_dispatch"]["h0/w0"]["phases"]["compact"] == 50
    # The watch line names it.
    assert "phase=compact:50%" in format_snapshot(snap)
