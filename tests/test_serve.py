"""Serve daemon (tpu_tree_search/serve/): admission control, shape-class
program pooling (zero-recompile warm admissions), checkpoint-based
preemption bit-identity, SIGTERM drain, and the thin CLI clients.

Everything runs on the virtual CPU platform with small shapes; the
daemon under test is in-process (port 0) except the SIGTERM drain test,
which needs a real process to kill."""

from __future__ import annotations

import json
import os
import re
import signal
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

from tpu_tree_search.engine.checkpoint import RunController
from tpu_tree_search.serve import jobs as serve_jobs
from tpu_tree_search.serve import pool as serve_pool
from tpu_tree_search.serve.jobs import JobRegistry, validate_spec
from tpu_tree_search.serve.scheduler import EnvLease
from tpu_tree_search.serve.server import ServeDaemon

_FINAL = ("done", "failed", "cancelled")

# One small shape shared across daemon tests: each daemon builds its own
# problem instance, so distinct shapes would multiply CPU compiles.
NQ10 = {"problem": "nqueens", "N": 10, "M": 256}


def _post(base, path, payload):
    req = urllib.request.Request(
        base + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode())


def _get(base, path):
    try:
        with urllib.request.urlopen(base + path, timeout=30) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode())


def _wait_final(base, jid, timeout_s=180.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        code, rec = _get(base, f"/job/{jid}")
        assert code == 200, rec
        if rec["state"] in _FINAL:
            return rec
        time.sleep(0.1)
    raise AssertionError(f"job {jid} did not finish in {timeout_s}s")


@pytest.fixture
def daemon(tmp_path):
    d = ServeDaemon(port=0, state_dir=str(tmp_path / "state"))
    d.start()
    yield d
    d.scheduler.drain(timeout_s=30.0)
    d.close()


def _reference(N, M, K=None):
    """Standalone resident_search on a FRESH problem (what a one-shot
    `tts run` computes)."""
    from tpu_tree_search.engine.resident import resident_search
    from tpu_tree_search.problems import NQueensProblem

    kw = {"K": K} if K is not None else {}
    return resident_search(NQueensProblem(N=N), m=25, M=M, **kw)


# -- spec validation + shape classes (pure host) -----------------------------


def test_validate_spec_defaults():
    spec = validate_spec({"problem": "nqueens"})
    assert spec["N"] == 14 and spec["g"] == 1 and spec["m"] == 25
    assert spec["tier"] == "device" and spec["M"] > 0
    spec = validate_spec({"problem": "pfsp", "lb": "lb2"})
    assert spec["inst"] == 14 and spec["ub"] == 1
    assert spec["lb2_variant"] == "full"


@pytest.mark.parametrize("bad", [
    {"problem": "tsp"},
    {"problem": "nqueens", "tier": "dist"},
    {"problem": "nqueens", "nope": 1},
    {"problem": "nqueens", "N": 2},
    {"problem": "nqueens", "K": 0},
    {"problem": "nqueens", "K": "fast"},
    {"problem": "pfsp", "lb2_variant": "lageweg"},  # needs lb=lb2
    {"problem": "pfsp", "lb": "lb1", "lb2_pairblock": 4},
    {"problem": "nqueens", "mp": 2},  # mesh-only knob on device tier
    {"problem": "nqueens", "M": "big"},
    [1, 2],
])
def test_validate_spec_rejects(bad):
    with pytest.raises(ValueError):
        validate_spec(bad)


def test_class_key_is_stable_and_shape_sensitive():
    a = serve_pool.class_key(validate_spec(dict(NQ10)))
    b = serve_pool.class_key(validate_spec(dict(NQ10)))
    assert a == b
    c = serve_pool.class_key(validate_spec({**NQ10, "M": 512}))
    assert c != a
    d = serve_pool.class_key(validate_spec({**NQ10, "compact": "scatter"}))
    assert "compact=scatter" in d and d != a


def test_class_key_resolves_knobs_without_env_mutation(monkeypatch):
    monkeypatch.delenv("TTS_COMPACT", raising=False)
    before = dict(os.environ)
    spec = validate_spec({"problem": "pfsp", "lb": "lb2", "M": 512,
                          "lb2_pairblock": "auto"})
    key = serve_pool.class_key(spec)
    # auto pairblock resolved to a concrete block size in the token.
    assert re.search(r"-pb\d+$", key), key
    assert dict(os.environ) == before


def test_identity_sharing_across_classes():
    pool = serve_pool.ProgramPool()
    e1 = pool.admit(validate_spec(dict(NQ10)))
    e2 = pool.admit(validate_spec({**NQ10, "M": 512}))
    e3 = pool.admit(validate_spec(dict(NQ10)))
    assert e1.problem is e2.problem  # same identity, different class
    assert e1 is e3 and e3.jobs_admitted == 2


# -- RunController yield seam ------------------------------------------------


def test_runcontroller_yield_fn_cuts(tmp_path):
    calls = []

    class P:  # minimal problem stand-in for problem_meta
        name = "nqueens"
        N = 4
        g = 1

    def yield_fn():
        calls.append(1)
        return len(calls) >= 3

    rc = RunController(P(), None, interval_s=1e9, max_steps=None,
                       snapshot_fn=lambda: (_ for _ in ()).throw(
                           AssertionError("no snapshot without a path")),
                       yield_fn=yield_fn)
    assert rc.after_step(1, 0) is False
    assert rc.after_step(2, 0) is False
    assert rc.after_step(3, 0) is True  # yield_fn went true -> cut
    assert len(calls) == 3  # checked at every dispatch boundary
    # Without yield_fn or max_steps, never cuts.
    rc2 = RunController(P(), None, interval_s=1e9, max_steps=None,
                        snapshot_fn=lambda: None)
    assert all(not rc2.after_step(i, 0) for i in range(50))


# -- env lease ---------------------------------------------------------------


def test_env_lease_serializes_conflicting_pins(monkeypatch):
    monkeypatch.delenv("TTS_TEST_PIN", raising=False)
    lease = EnvLease()
    order = []
    lease.acquire({"TTS_TEST_PIN": "a"})
    assert os.environ["TTS_TEST_PIN"] == "a"
    lease.acquire({"TTS_TEST_PIN": "a"})  # identical pins share

    def conflicting():
        lease.acquire({"TTS_TEST_PIN": "b"})
        order.append(os.environ["TTS_TEST_PIN"])
        lease.release()

    t = threading.Thread(target=conflicting, daemon=True)
    t.start()
    time.sleep(0.3)
    assert not order  # blocked while 'a' holders are live
    lease.release()
    lease.release()
    t.join(timeout=10)
    assert order == ["b"]
    assert "TTS_TEST_PIN" not in os.environ  # restored after last release


# -- registry durability -----------------------------------------------------


def test_transition_if_refuses_stale_state(tmp_path):
    """The CAS transition that keeps a racing cancel and a worker's queue
    pop coherent: the loser must no-op, never resurrect a terminal job."""
    reg = JobRegistry(str(tmp_path))
    job = reg.create(validate_spec(dict(NQ10)), "cls", {})
    assert reg.transition_if(job, ("queued", "requeued"), "cancelled")
    # The worker's raced running transition loses and changes nothing.
    assert not reg.transition_if(job, ("queued", "requeued"), "running")
    assert job.state == "cancelled"
    reg2 = JobRegistry(str(tmp_path))
    reg2.load()
    assert reg2.get(job.id).state == "cancelled"


def test_concurrent_persists_never_tear_the_record(tmp_path):
    """Concurrent transitions of ONE job (HTTP cancel racing a worker
    update) must each write through their own tmp file under the io lock —
    interleaved writes through a shared tmp path used to rename torn JSON
    into place, which load() then silently dropped."""
    reg = JobRegistry(str(tmp_path))
    job = reg.create(validate_spec(dict(NQ10)), "cls", {})
    stop = threading.Event()

    def hammer(field):
        i = 0
        while not stop.is_set():
            i += 1
            reg.update(job, **{field: i})

    threads = [threading.Thread(target=hammer, args=(f,), daemon=True)
               for f in ("preemptions", "slices", "new_programs")]
    for t in threads:
        t.start()
    time.sleep(0.5)
    stop.set()
    for t in threads:
        t.join(timeout=10)
    reg2 = JobRegistry(str(tmp_path))
    assert reg2.load() == 1  # the record parses — never a torn write


def test_registry_durability_reload(tmp_path):
    reg = JobRegistry(str(tmp_path))
    spec = validate_spec(dict(NQ10))
    j1 = reg.create(spec, "cls", {})
    j2 = reg.create(spec, "cls", {})
    j3 = reg.create(spec, "cls", {})
    reg.transition(j1, "done", result={"explored_tree": 1})
    reg.transition(j2, "running")
    # j3 stays queued; a new registry on the same dir models a restart.
    reg2 = JobRegistry(str(tmp_path))
    assert reg2.load() == 3
    assert reg2.get(j1.id).state == "done"
    assert reg2.get(j1.id).result == {"explored_tree": 1}
    assert reg2.get(j2.id).state == "requeued"  # was running
    assert reg2.get(j3.id).state == "requeued"  # was queued
    # New ids continue past the loaded sequence.
    j4 = reg2.create(spec, "cls", {})
    assert j4.id > j3.id


# -- e2e: submit/stream/result + bit-identity vs the standalone CLI ---------


def test_e2e_submit_stream_result_bit_identical_to_cli(daemon, capsys):
    from tpu_tree_search import cli

    rc = cli.main(["nqueens", "--N", "10", "--M", "256",
                   "--tier", "device", "--json"])
    assert rc == 0
    cli_rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])

    base = daemon.url
    code, sub = _post(base, "/submit", NQ10)
    assert code == 201 and sub["warm"] is False
    # Stream the job: snapshot frames then the final record as `done`.
    from tpu_tree_search.obs.live import iter_sse

    frames, incumbents, final = [], [], None
    with urllib.request.urlopen(
        base + f"/job/{sub['id']}/stream", timeout=180
    ) as resp:
        for event, payload in iter_sse(resp):
            if event == "done":
                final = payload
                break
            if event == "incumbent":  # quality frames ride the same stream
                incumbents.append(payload)
                continue
            frames.append(payload)
    assert final is not None and final["state"] == "done"
    assert frames, "expected at least one snapshot frame"
    assert frames[-1]["tier"] == "resident"
    assert incumbents and incumbents[0]["job"] == sub["id"]
    assert final["result"]["explored_tree"] == cli_rec["explored_tree"]
    assert final["result"]["explored_sol"] == cli_rec["explored_sol"]
    # /result agrees with the stream's terminal frame.
    code, res = _get(base, f"/job/{sub['id']}/result")
    assert code == 200 and res["result"] == final["result"]


def test_result_conflicts_until_done(daemon):
    base = daemon.url
    code, sub = _post(base, "/submit", {**NQ10, "N": 12, "K": 4})
    code, res = _get(base, f"/job/{sub['id']}/result")
    assert code == 409 and "state" in res
    _wait_final(base, sub["id"])
    code, res = _get(base, f"/job/{sub['id']}/result")
    assert code == 200


def test_unknown_job_and_bad_spec(daemon):
    base = daemon.url
    assert _get(base, "/job/nope")[0] == 404
    code, err = _post(base, "/submit", {"problem": "tsp"})
    assert code == 400 and "error" in err
    code, err = _post(base, "/submit", ["not", "a", "dict"])
    assert code == 400


def test_queue_admission_control(tmp_path):
    d = ServeDaemon(port=0, state_dir=str(tmp_path / "s"), max_queue=1)
    # Scheduler NOT started: jobs stay queued, so the cap is observable.
    d._http_thread = threading.Thread(
        target=d._httpd.serve_forever, kwargs={"poll_interval": 0.2},
        daemon=True)
    d._http_thread.start()
    try:
        base = d.url
        assert _post(base, "/submit", NQ10)[0] == 201
        code, err = _post(base, "/submit", NQ10)
        assert code == 503 and "queue full" in err["error"]
    finally:
        d.close()


# -- zero-recompile warm-class admission (the tentpole acceptance) -----------


def test_second_same_class_job_zero_recompiles_under_guard(
    tmp_path, monkeypatch
):
    # TTS_GUARD=1 for the daemon's whole life: every steady-state dispatch
    # of every job slice asserts zero recompiles + zero implicit
    # transfers. A violation fails the job, which fails the test.
    monkeypatch.setenv("TTS_GUARD", "1")
    d = ServeDaemon(port=0, state_dir=str(tmp_path / "state"))
    d.start()
    try:
        base = d.url
        code, s1 = _post(base, "/submit", NQ10)
        rec1 = _wait_final(base, s1["id"])
        assert rec1["state"] == "done", rec1["error"]
        assert rec1["new_programs"] >= 1  # cold class compiled
        code, s2 = _post(base, "/submit", NQ10)
        assert s2["warm"] is True and s2["class"] == s1["class"]
        rec2 = _wait_final(base, s2["id"])
        assert rec2["state"] == "done", rec2["error"]
        # The acceptance criterion: a warm-class admission compiles
        # NOTHING — no new program-cache entries, no new jit entries.
        assert rec2["new_programs"] == 0
        assert rec2["new_step_compiles"] == 0
        assert rec2["result"]["explored_tree"] == rec1["result"]["explored_tree"]
        code, classes = _get(base, "/classes")
        entry = next(c for c in classes if c["class"] == s1["class"])
        assert entry["warm"] and entry["jobs_admitted"] == 2
    finally:
        d.scheduler.drain(timeout_s=30.0)
        d.close()


# -- preemption --------------------------------------------------------------


def test_preempt_resume_bit_identity(tmp_path):
    ref = _reference(N=11, M=256, K=4)
    # quantum=0: every dispatch boundary with waiting work preempts.
    d = ServeDaemon(port=0, state_dir=str(tmp_path / "state"), quantum_s=0.0)
    d.start()
    try:
        base = d.url
        code, p1 = _post(base, "/submit",
                         {"problem": "nqueens", "N": 11, "M": 256, "K": 4})
        code, p2 = _post(base, "/submit", {**NQ10, "K": 4})
        rec1 = _wait_final(base, p1["id"])
        rec2 = _wait_final(base, p2["id"])
        assert rec1["state"] == "done" and rec2["state"] == "done"
        assert rec1["preemptions"] > 0, "quantum=0 with a queue must preempt"
        assert rec1["slices"] == rec1["preemptions"] + 1
        # Preempted-and-resumed totals == the uninterrupted run's, exactly.
        assert rec1["result"]["explored_tree"] == ref.explored_tree
        assert rec1["result"]["explored_sol"] == ref.explored_sol
        assert rec1["result"]["best"] == ref.best
        # Checkpoints are consumed: nothing dangling after completion.
        assert rec1["checkpoint"] is None
    finally:
        d.scheduler.drain(timeout_s=30.0)
        d.close()


def test_max_steps_budget_survives_preemption(tmp_path):
    """max_steps is a cumulative budget across slices: with quantum=0 and
    competing work, the job is preempted mid-budget and must resume with
    the remainder — finishing 'done' only once the whole budget is spent,
    never at its first cut."""
    d = ServeDaemon(port=0, state_dir=str(tmp_path / "state"), quantum_s=0.0)
    d.start()
    try:
        base = d.url
        _, s1 = _post(base, "/submit",
                      {"problem": "nqueens", "N": 12, "M": 256, "K": 2,
                       "max_steps": 6})
        _, s2 = _post(base, "/submit", NQ10)  # the waiter that forces cuts
        rec1 = _wait_final(base, s1["id"])
        rec2 = _wait_final(base, s2["id"])
        assert rec2["state"] == "done", rec2["error"]
        assert rec1["state"] == "done", rec1["error"]
        assert rec1["preemptions"] > 0, "quantum=0 with a queue must preempt"
        # The budget was consumed across slices, exactly — a preemption cut
        # was not passed off as the max_steps cutoff.
        assert rec1["steps"] == 6
        assert rec1["result"]["complete"] is False
    finally:
        d.scheduler.drain(timeout_s=30.0)
        d.close()


def test_checkpoint_fetch_gzip_negotiated(tmp_path):
    """``GET /job/<id>/checkpoint`` serves identity bytes to plain clients
    and gzip to clients that ask (Accept-Encoding) — the `tts migrate`
    transport. Both encodings must decode to the exact on-disk npz: a
    migrated job's resume is bit-identity-critical, so the compression is
    transport-only."""
    import gzip

    d = ServeDaemon(port=0, state_dir=str(tmp_path / "state"))
    d.start()
    try:
        base = d.url
        # A cancelled-mid-run job is the migrate source state: the cut
        # leaves a live checkpoint (done jobs delete theirs).
        _, sub = _post(base, "/submit",
                       {"problem": "nqueens", "N": 13, "M": 256, "K": 2,
                        "max_steps": 1 << 20})
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            _, rec = _get(base, f"/job/{sub['id']}")
            if rec["state"] == "running":
                break
            time.sleep(0.05)
        assert rec["state"] == "running"
        time.sleep(0.5)  # let a dispatch land so the cut has a frontier
        code, _resp = _post(base, f"/job/{sub['id']}/cancel", {})
        assert code == 200
        rec = _wait_final(base, sub["id"])
        assert rec["state"] == "cancelled" and rec["checkpoint"]
        disk = open(rec["checkpoint"], "rb").read()
        with urllib.request.urlopen(
                base + f"/job/{sub['id']}/checkpoint", timeout=30) as r:
            assert r.headers.get("Content-Encoding") is None
            assert r.read() == disk
        req = urllib.request.Request(
            base + f"/job/{sub['id']}/checkpoint",
            headers={"Accept-Encoding": "gzip"})
        with urllib.request.urlopen(req, timeout=30) as r:
            assert r.headers.get("Content-Encoding") == "gzip"
            wire = r.read()
            assert int(r.headers["Content-Length"]) == len(wire)
        assert gzip.decompress(wire) == disk
    finally:
        d.scheduler.drain(timeout_s=30.0)
        d.close()


def test_cancel_max_steps_job_ends_cancelled(daemon):
    """A cancelled max_steps job must report 'cancelled' — its yield cut
    used to be indistinguishable from the max_steps cutoff, recording a
    silently truncated result as 'done' and deleting the checkpoint."""
    base = daemon.url
    _, sub = _post(base, "/submit",
                   {"problem": "nqueens", "N": 13, "M": 256, "K": 2,
                    "max_steps": 1 << 20})
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        _, rec = _get(base, f"/job/{sub['id']}")
        if rec["state"] == "running":
            break
        time.sleep(0.05)
    assert rec["state"] == "running"
    code, _resp = _post(base, f"/job/{sub['id']}/cancel", {})
    assert code == 200
    rec = _wait_final(base, sub["id"])
    assert rec["state"] == "cancelled"
    assert rec["steps"] < (1 << 20)


def test_drain_requeues_running_max_steps_job(tmp_path):
    """Daemon drain with a max_steps job in flight: the cut slice must be
    requeued with its checkpoint (resumable mid-budget), not recorded
    'done' with partial counters."""
    d = ServeDaemon(port=0, state_dir=str(tmp_path / "state"))
    d.start()
    try:
        base = d.url
        _, sub = _post(base, "/submit",
                       {"problem": "nqueens", "N": 13, "M": 256, "K": 2,
                        "max_steps": 1 << 20})
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            _, rec = _get(base, f"/job/{sub['id']}")
            if rec["state"] == "running":
                break
            time.sleep(0.05)
        assert rec["state"] == "running"
        time.sleep(0.5)  # let some dispatches land
        d.scheduler.drain(timeout_s=60.0)
        job = d.registry.get(sub["id"])
        assert job.state == "requeued"
        assert job.steps < (1 << 20)
        assert job.checkpoint and os.path.exists(job.checkpoint)
    finally:
        d.close()


def test_worker_survives_admit_failure(tmp_path):
    """A per-job failure OUTSIDE the search call (admission, problem
    construction) must fail the job, not kill the worker — with the
    default --workers 1 a dead worker leaves a daemon that accepts
    submits but never runs another job."""
    d = ServeDaemon(port=0, state_dir=str(tmp_path / "state"))
    orig_admit = d.pool.admit

    def boom(spec):
        raise RuntimeError("synthetic admit failure")

    d.pool.admit = boom
    d.start()
    try:
        base = d.url
        _, sub = _post(base, "/submit", NQ10)
        rec = _wait_final(base, sub["id"])
        assert rec["state"] == "failed"
        assert "synthetic admit failure" in rec["error"]
        d.pool.admit = orig_admit
        _, sub2 = _post(base, "/submit", NQ10)
        rec2 = _wait_final(base, sub2["id"])
        assert rec2["state"] == "done", rec2["error"]
    finally:
        d.scheduler.drain(timeout_s=30.0)
        d.close()


def test_cancel_running_job(daemon):
    base = daemon.url
    code, sub = _post(base, "/submit",
                      {"problem": "nqueens", "N": 13, "M": 256, "K": 2})
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        _, rec = _get(base, f"/job/{sub['id']}")
        if rec["state"] == "running":
            break
        time.sleep(0.05)
    assert rec["state"] == "running"
    code, resp = _post(base, f"/job/{sub['id']}/cancel", {})
    assert code == 200
    rec = _wait_final(base, sub["id"])
    assert rec["state"] == "cancelled"
    # Partial progress is reported (complete=False counters).
    assert rec["result"] is None or rec["result"]["complete"] is False
    # Cancelling again: already terminal.
    code, resp = _post(base, f"/job/{sub['id']}/cancel", {})
    assert code == 409


def test_cancel_queued_job_never_runs(tmp_path):
    d = ServeDaemon(port=0, state_dir=str(tmp_path / "s"))
    # No scheduler: the job stays queued.
    d._http_thread = threading.Thread(
        target=d._httpd.serve_forever, kwargs={"poll_interval": 0.2},
        daemon=True)
    d._http_thread.start()
    try:
        base = d.url
        _, sub = _post(base, "/submit", NQ10)
        code, _resp = _post(base, f"/job/{sub['id']}/cancel", {})
        assert code == 200
        _, rec = _get(base, f"/job/{sub['id']}")
        assert rec["state"] == "cancelled" and rec["slices"] == 0
    finally:
        d.close()


# -- concurrent multi-tenant smoke ------------------------------------------


def test_three_concurrent_jobs_bit_identical(daemon):
    refs = {N: _reference(N=N, M=256) for N in (9, 10, 11)}
    base = daemon.url
    subs = {}
    for N in (11, 9, 10):  # deliberately not id order
        _, sub = _post(base, "/submit",
                       {"problem": "nqueens", "N": N, "M": 256})
        subs[N] = sub["id"]
    for N, jid in subs.items():
        rec = _wait_final(base, jid)
        assert rec["state"] == "done", rec["error"]
        assert rec["result"]["explored_tree"] == refs[N].explored_tree
        assert rec["result"]["explored_sol"] == refs[N].explored_sol
    _, health = _get(base, "/healthz")
    assert health["ok"] and health["jobs"] == 3


# -- SIGTERM drain (subprocess) ---------------------------------------------


def test_sigterm_drains_running_job_to_requeued(tmp_path):
    """The daemon's graceful-drain contract: SIGTERM with a job in flight
    cuts it at the next dispatch boundary (checkpoint written), marks it
    requeued, dumps the flight recorder (TTS_FLIGHTREC composition), and
    exits 0."""
    state = tmp_path / "state"
    prefix = tmp_path / "fr"
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "TTS_FLIGHTREC": str(prefix)}
    proc = subprocess.Popen(
        [sys.executable, "-m", "tpu_tree_search.cli", "serve", "--port", "0",
         "--state-dir", str(state)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
    )
    try:
        banner = proc.stdout.readline()
        m = re.search(r"http://127\.0\.0\.1:(\d+)", banner)
        assert m, banner
        base = f"http://127.0.0.1:{m.group(1)}"
        _, sub = _post(base, "/submit",
                       {"problem": "nqueens", "N": 13, "M": 256, "K": 2})
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            _, rec = _get(base, f"/job/{sub['id']}")
            if rec["state"] == "running":
                break
            time.sleep(0.1)
        assert rec["state"] == "running"
        time.sleep(1.0)  # let some dispatches land
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=90)
        assert rc == 0, proc.stdout.read()
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
    # Durable record: requeued, with a live checkpoint to resume from.
    rec = json.load(open(state / "jobs" / f"{sub['id']}.json"))
    assert rec["state"] == "requeued"
    assert rec["checkpoint"] and os.path.exists(rec["checkpoint"])
    # Flight-recorder SIGTERM dump composed with the drain handler.
    assert (tmp_path / "fr.trace.json").exists()


# -- warmup ------------------------------------------------------------------


def test_warmup_select_configs():
    from tpu_tree_search.serve import warmup

    assert len(warmup.select_configs(None)) == len(warmup.CONFIGS)
    serveable = warmup.select_configs("serve")
    assert serveable and all(c.servable for c in serveable)
    two = warmup.select_configs("ta014-lb1,nqueens-15")
    assert [c.name for c in two] == ["ta014-lb1", "nqueens-15"]
    with pytest.raises(ValueError):
        warmup.select_configs("no-such-config")
    # Every serve-able config produces a valid spec (admission-compatible).
    for cfg in serveable:
        validate_spec(cfg.spec())


def test_warmup_main_rejects_unknown_names(capsys):
    from tpu_tree_search.serve.warmup import warmup_main

    assert warmup_main("definitely-not-a-config") == 2
    assert "unknown warm config" in capsys.readouterr().err


@pytest.mark.slow
def test_warmup_hit_miss_accounting(tmp_path, monkeypatch):
    """A config's first subprocess run banks new compile-cache files
    (miss); an identical second run compiles nothing (hit)."""
    from tpu_tree_search.serve.warmup import WarmConfig, run_configs

    monkeypatch.setenv("TTS_COMPILE_CACHE", str(tmp_path / "xla"))
    # CPU test compiles are sub-second; drop the persistence floor so
    # they land in the cache and the delta is observable.
    monkeypatch.setenv("TTS_WARM_MIN_COMPILE_S", "0")
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    cfg = [WarmConfig("tiny", "tiny nqueens", ["nqueens", "8", "64"])]
    lines = []
    assert run_configs(cfg, timeout_s=300, emit=lines.append) == 0
    assert re.search(r"miss\(\+\d+ files\)", lines[0]), lines
    lines2 = []
    assert run_configs(cfg, timeout_s=300, emit=lines2.append) == 0
    assert "[hit]" in lines2[0], lines2


# -- CLI surface -------------------------------------------------------------


def test_cli_submit_requires_run_command(capsys):
    from tpu_tree_search import cli

    with pytest.raises(SystemExit):
        cli.main(["submit"])
    with pytest.raises(SystemExit):
        cli.main(["submit", "--", "watch"])


def test_cli_submit_and_watch_job_roundtrip(daemon, capsys):
    from tpu_tree_search import cli

    rc = cli.main(["submit", "--port", str(daemon.port), "--wait", "--json",
                   "--", "nqueens", "--N", "10", "--M", "256"])
    out = capsys.readouterr().out.strip().splitlines()[-1]
    assert rc == 0
    rec = json.loads(out)
    assert rec["state"] == "done"
    assert rec["result"]["explored_tree"] > 0
    # seq (the parser default) submits as the device tier.
    assert rec["spec"]["tier"] == "device"
    rc = cli.main(["watch", "--job", rec["id"], "--port", str(daemon.port),
                   "--json"])
    assert rc == 0
    watched = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert watched["id"] == rec["id"] and watched["state"] == "done"


def test_cli_watch_job_unreachable():
    from tpu_tree_search import cli

    # A port nothing listens on: clean error exit, no traceback.
    assert cli.main(["watch", "--job", "job-000001",
                     "--port", "1"]) == 2
