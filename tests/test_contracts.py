"""The compiled-program contract registry + `tts check` auditor (ISSUE 8).

Three layers:

* registry mechanics (declaration, collision rejection, the >= 12 bar);
* **tamper tests** — mutate each contract class's subject (inject a sort
  into dense compaction, drop the donation, fork / collapse a cache key,
  leak telemetry into the off path, serialize the pair axis, drift an op
  fingerprint, build a lock cycle) and assert `tts check` fails with the
  MATCHING named contract — the checker itself is what these tests test;
* CLI surfaces (`tts check --list`, a narrowed end-to-end run).

The full-matrix green run is CI's dedicated `tts check` job; tests here
stay on single cells so the tier-1 budget is untouched.
"""

from __future__ import annotations

import os
from pathlib import Path

import pytest

from tpu_tree_search.analysis import contracts, program_audit
from tpu_tree_search.ops import compaction

FIXTURES = Path(__file__).parent / "data" / "lint"

program_audit.load_contracts()


# -- registry mechanics ----------------------------------------------------


def test_registry_meets_the_contract_bar():
    reg = program_audit.load_contracts()
    assert len(reg) >= 12
    assert {
        "dense-step-no-sort-scatter", "dense-ids-shift-only",
        "fused-push-single-gather", "pool-donation",
        "step-callback-armed-only", "program-cache-key-sound",
        "lb2-pairblock-loop-free", "obs-off-identity", "obs-counter-block",
        "phaseprof-off-identity", "pipeline-knob-inert", "guard-knob-inert",
        "lock-order-acyclic", "op-fingerprint",
    } <= set(reg)
    # Declared next to the code they pin, not centrally.
    assert reg["dense-step-no-sort-scatter"].declared_in.endswith(
        "ops.compaction")
    assert reg["pool-donation"].declared_in.endswith("engine.resident")
    assert reg["obs-off-identity"].declared_in.endswith("obs.counters")


def test_contract_name_collision_rejected():
    with pytest.raises(ValueError, match="already declared"):
        contracts.contract(
            "pool-donation", claim="imposter", artifact="resident-step"
        )(lambda a, c: [])


def test_unknown_contract_name_raises():
    with pytest.raises(KeyError, match="unknown contract"):
        contracts.get("no-such-contract")


# -- tamper tests: each contract class must catch its injected violation ---


def test_tamper_sort_injected_into_dense_compaction(monkeypatch):
    """Re-route the dense rank inversion through the sort implementation:
    the dense-path contract must name the smuggled sort."""
    real = compaction.compact_ids

    def tampered(keep, S, mode):
        return real(keep, S, "sort" if mode == "dense" else mode)

    monkeypatch.setattr(compaction, "compact_ids", tampered)
    cell = program_audit.Cell("nqueens", compact="dense")
    art = program_audit.trace_cell(cell)
    msgs = contracts.run_one("dense-step-no-sort-scatter", art, cell)
    assert msgs and "sort" in msgs[0], msgs


def test_tamper_broken_donation(monkeypatch):
    """Rebuild the step without donate_argnums: the donation contract must
    notice the aliasing is gone from the lowered program."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from tpu_tree_search.engine import resident
    from tpu_tree_search.obs import counters as obs_counters
    from tpu_tree_search.obs import phases as obs_phases

    def build_nodonate(self):
        cond, body = self.loop_fns()
        obs, phaseprof = self.obs, self.phaseprof

        def step(pool_vals, pool_aux, size, best):
            zero = jnp.int32(0)
            init = (pool_vals, pool_aux, size, best, zero, zero, zero)
            if obs:
                init = init + (obs_counters.init_block(),)
            if phaseprof:
                init = init + (
                    obs_phases.seed_block(size.astype(jnp.uint32)),
                )
            return lax.while_loop(cond, body, init)

        return jax.jit(step)  # the tamper: donation dropped

    monkeypatch.setattr(resident._ResidentProgram, "_build", build_nodonate)
    cell = program_audit.Cell("nqueens")
    art = program_audit.trace_cell(cell)
    msgs = contracts.run_one("pool-donation", art, cell)
    assert msgs and "donation" in msgs[0], msgs


def test_tamper_cache_key_collapsed(monkeypatch):
    """Make the program cache blind to TTS_OBS: the cache-key contract
    must report the flip reusing a stale program."""
    from tpu_tree_search.obs import counters as obs_counters

    monkeypatch.setattr(obs_counters, "device_counters_enabled",
                        lambda: False)
    art = program_audit.cache_key_artifact("nqueens")
    msgs = contracts.run_one("program-cache-key-sound", art)
    assert any("TTS_OBS" in m and "reused" in m for m in msgs), msgs


def test_tamper_cache_key_forked_by_host_knob(monkeypatch):
    """Leak the host-only TTS_PIPELINE knob into the routing token: the
    cache-key contract must report the forked compilation."""
    from tpu_tree_search.ops import pfsp_device as P

    real = P.routing_cache_token
    monkeypatch.setattr(
        P, "routing_cache_token",
        lambda problem, device=None: real(problem, device)
        + (os.environ.get("TTS_PIPELINE"),),
    )
    art = program_audit.cache_key_artifact("nqueens")
    msgs = contracts.run_one("program-cache-key-sound", art)
    assert any("TTS_PIPELINE" in m and "rebuilt" in m for m in msgs), msgs


def test_tamper_counters_leak_into_off_path(monkeypatch):
    """Force the counter block on unconditionally: the off-identity
    contract must notice the off build is no longer the 7-leaf carry."""
    from tpu_tree_search.obs import counters as obs_counters

    monkeypatch.setattr(obs_counters, "device_counters_enabled",
                        lambda: True)
    art = program_audit.variant_artifact(
        "nqueens", labels=["off", "obs0", "obs-host", "obs1"]
    )
    msgs = contracts.run_one("obs-off-identity", art)
    assert msgs and "7" in " ".join(msgs), msgs


def test_tamper_phase_clock_in_unarmed_step(monkeypatch):
    """Force the phase profiler on unconditionally: the callback contract
    must flag the clock callback inside an unarmed steady-state cell."""
    from tpu_tree_search.obs import phases as obs_phases

    monkeypatch.setattr(obs_phases, "phase_profiling_enabled", lambda: True)
    cell = program_audit.Cell("nqueens", phaseprof="0")
    art = program_audit.trace_cell(cell)
    msgs = contracts.run_one("step-callback-armed-only", art, cell)
    assert msgs and "callback" in msgs[0], msgs


def test_tamper_pair_axis_serialized(monkeypatch):
    """Collapse the auto pair-block policy to the serial loop: the
    pair-axis contract must fail at the published blocked shape."""
    from tpu_tree_search.ops import pfsp_device as P

    monkeypatch.setattr(P, "lb2_pairblock", lambda Pn, n: 1)
    findings = program_audit.audit_lb2_eval(pairblocks=(None,))
    assert findings, "serialized pair axis not caught"
    assert all(f.rule == "contract:lb2-pairblock-loop-free"
               for f in findings)


def test_tamper_fingerprint_drift():
    """An op histogram differing from the committed baseline must fail
    with the named cell and a per-op diff."""
    import jax

    baseline = {
        "jax": jax.__version__,
        "cells": {"cellA": {"ops": {"gather": 1, "while": 1}, "outvars": 7}},
    }
    current = {"cellA": {"ops": {"gather": 2, "while": 1}, "outvars": 7}}
    msgs = contracts.run_one(
        "op-fingerprint",
        {"current": current, "baseline": baseline, "path": "x.json"},
    )
    assert msgs == ["cellA: op drift — gather: 1 -> 2"], msgs
    # outvar drift and missing/stale cells are also named
    current2 = {"cellA": {"ops": {"gather": 1, "while": 1}, "outvars": 8},
                "cellB": {"ops": {}}}
    msgs2 = contracts.run_one(
        "op-fingerprint",
        {"current": current2, "baseline": baseline, "path": "x.json"},
    )
    assert any("outvar count 7 -> 8" in m for m in msgs2)
    assert any("cellB" in m and "missing" in m for m in msgs2)
    # no baseline at all: actionable, not a crash
    msgs3 = contracts.run_one(
        "op-fingerprint",
        {"current": current, "baseline": None, "path": "x.json"},
    )
    assert msgs3 and "--update" in msgs3[0]


def test_tamper_lock_cycle_detected():
    """A deliberate A->B / B->A blocking cycle must fail the lock-order
    contract (and the same fixture drives the lint-rule test in
    tests/test_lint.py)."""
    findings = program_audit.audit_locks(
        paths=[str(FIXTURES / "bad_lock_order.py")]
    )
    assert findings, "lock cycle not caught"
    assert all(f.rule == "contract:lock-order-acyclic" for f in findings)
    text = " ".join(f.message for f in findings)
    assert "A.lock -> B.lock -> A.lock" in text
    assert "same-class" in text


def test_repo_lock_graph_is_clean():
    """The acceptance bar: zero acquisition cycles across the
    lock-bearing host runtime — pool/, parallel/, and the KV/event/
    recorder layers.  (The whole-package run is test_lint's single full
    scan; scoping here keeps the contract test's parse cost down.)"""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(
        program_audit.__file__)))
    findings = program_audit.audit_locks(paths=[
        os.path.join(pkg, "pool"),
        os.path.join(pkg, "parallel"),
        os.path.join(pkg, "obs"),
        os.path.join(pkg, "engine"),
    ])
    assert findings == [], [f.render() for f in findings]


# -- audit mechanics -------------------------------------------------------


def test_matrix_cells_cover_every_axis():
    cells = program_audit.matrix_cells()
    keys = {c.key for c in cells}
    assert len(keys) == len(cells)  # no duplicate cells
    fams = {c.family for c in cells}
    assert fams == set(program_audit.FAMILIES)
    # every lb2 cell carries the pair-block axis, nobody else does
    for c in cells:
        assert (c.pairblock is not None) == (c.family == "pfsp-lb2")
    compacts = {c.compact for c in cells}
    assert compacts == set(program_audit.COMPACT_AXIS)


def test_pin_is_hermetic(monkeypatch):
    """The audit's env pin must isolate from CI matrix pins (TTS_OBS=1 /
    TTS_COMPACT=sort jobs run this suite too) and restore afterwards."""
    monkeypatch.setenv("TTS_COMPACT", "sort")
    monkeypatch.setenv("TTS_OBS", "1")
    with program_audit._pin({"TTS_PHASEPROF": "1"}):
        assert os.environ.get("TTS_COMPACT") is None
        assert os.environ.get("TTS_OBS") is None
        assert os.environ.get("TTS_PHASEPROF") == "1"
    assert os.environ.get("TTS_COMPACT") == "sort"
    assert os.environ.get("TTS_OBS") == "1"


def test_committed_baseline_is_loadable_and_hashed():
    doc = program_audit.load_baseline(
        str(Path(program_audit.__file__).parents[2] / ".tts-contracts.json")
    )
    assert doc is not None, "commit .tts-contracts.json (tts check --update)"
    assert doc["fingerprint"] == program_audit._hash_cells(doc["cells"])
    assert len(doc["cells"]) >= 100  # the full matrix, not a stub
    fp = program_audit.committed_fingerprint(
        str(Path(program_audit.__file__).parents[2] / ".tts-contracts.json")
    )
    assert fp == doc["fingerprint"]


# -- CLI surfaces ----------------------------------------------------------


def test_cli_check_list(capsys):
    from tpu_tree_search import cli

    assert cli.main(["check", "--list"]) == 0
    out = capsys.readouterr().out
    assert "dense-step-no-sort-scatter" in out
    assert "lock-order-acyclic" in out


@pytest.mark.slow  # ~20 s of tracing; CI's `tts check` job runs the FULL matrix
def test_cli_check_family_end_to_end(tmp_path, capsys):
    """A narrowed end-to-end run: one family, contracts only (the
    whole-matrix fingerprint gate is CI's dedicated job)."""
    from tpu_tree_search import cli

    rc = cli.main(["check", "--family", "nqueens", "--no-locks"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "0 finding(s)" in out


def test_cli_check_rejects_update_with_family(capsys):
    from tpu_tree_search import cli

    assert cli.main(["check", "--update", "--family", "nqueens"]) == 2


def test_cli_check_update_roundtrip(tmp_path, monkeypatch, capsys):
    """--update writes a loadable baseline whose hash matches its cells
    (family-scoped into a temp file — never the committed one)."""
    bl = tmp_path / "contracts.json"
    res = program_audit.run_check(
        families=["nqueens"], update=True, baseline_path=str(bl),
        with_locks=False,
    )
    assert res.findings == [], [f.render() for f in res.findings]
    doc = program_audit.load_baseline(str(bl))
    assert doc is not None
    assert doc["fingerprint"] == program_audit._hash_cells(doc["cells"])
    assert res.updated == str(bl)
