"""Device-resident engine: parity against the sequential anchor.

The resident tier must reproduce the sequential tier's exploredTree /
exploredSol exactly whenever the incumbent is fixed (N-Queens never prunes;
PFSP with a preloaded optimal incumbent never improves it) — the same
determinism invariant the reference relies on across its tiers
(SURVEY.md §4.2). With an improving incumbent (ub=0) the resident tier is a
valid B&B relaxation: it must find the same optimum.
"""

from __future__ import annotations

import pytest

from tpu_tree_search.engine.resident import resident_search
from tpu_tree_search.engine.sequential import sequential_search
from tpu_tree_search.problems import NQueensProblem, PFSPProblem
from tpu_tree_search.problems.pfsp import taillard


def test_nqueens_parity():
    prob = NQueensProblem(N=10)
    seq = sequential_search(prob)
    res = resident_search(prob, m=8, M=256, K=64)
    assert (res.explored_tree, res.explored_sol) == (
        seq.explored_tree,
        seq.explored_sol,
    )


@pytest.mark.parametrize("mode", ["scatter", "sort", "search", "dense"])
def test_nqueens_overflow_fallback(mode, monkeypatch):
    # A warm frontier beyond the fan-out headroom forces the capacity-stall
    # path (host offload cycles until the pool fits again), and M=256 makes
    # breadth chunks exceed the survivor budget (S = M*n/2), covering the
    # full-scatter overflow branch; counts must not change. Parametrized
    # over TTS_COMPACT: the overflow branch bypasses the compacted ids, and
    # every mode must hand over to it identically.
    monkeypatch.setenv("TTS_COMPACT", mode)
    prob = NQueensProblem(N=11)
    seq = sequential_search(prob)
    res = resident_search(
        prob, m=8, M=256, K=16, capacity=8000, warmup_target=7500
    )
    assert (res.explored_tree, res.explored_sol) == (
        seq.explored_tree,
        seq.explored_sol,
    )
    # The stall path's offloader transfers must appear in the diagnostics.
    assert res.diagnostics.host_to_device > 1
    assert res.diagnostics.device_to_host > 1


@pytest.mark.parametrize("lb", ["lb1", "lb1_d", "lb2"])
def test_pfsp_fixed_incumbent_parity(lb):
    ptm = taillard.reduced_instance(14, jobs=10, machines=5)
    # Establish the optimum with the sequential engine, then run both tiers
    # with that fixed incumbent: counts must match node-for-node.
    opt = sequential_search(PFSPProblem(lb=lb, ub=0, p_times=ptm)).best
    seq = sequential_search(PFSPProblem(lb=lb, ub=0, p_times=ptm), initial_best=opt)
    res = resident_search(
        PFSPProblem(lb=lb, ub=0, p_times=ptm), m=8, M=256, K=64, initial_best=opt
    )
    assert res.best == seq.best == opt
    assert (res.explored_tree, res.explored_sol) == (
        seq.explored_tree,
        seq.explored_sol,
    )


def test_large_instance_shapes():
    # 50-job instance (gather fallback path, int8 pool rows): a prune-all
    # incumbent keeps the tree tiny so this only checks shapes/dtypes.
    ptm = taillard.reduced_instance(31, jobs=50, machines=10)
    prob = PFSPProblem(lb="lb1", ub=0, p_times=ptm)
    res = resident_search(prob, m=8, M=128, K=8, initial_best=1)
    assert res.complete
    assert res.best == 1  # nothing can beat a makespan of 1


@pytest.mark.parametrize("lb", ["lb1", "lb2"])
def test_pfsp_improving_incumbent_finds_optimum(lb):
    ptm = taillard.reduced_instance(7, jobs=9, machines=6)
    seq = sequential_search(PFSPProblem(lb=lb, ub=0, p_times=ptm))
    res = resident_search(PFSPProblem(lb=lb, ub=0, p_times=ptm), m=8, M=128, K=32)
    assert res.best == seq.best


def test_large_taillard_instances_run():
    """Job count is a runtime parameter, not a compile-time cap: ta031
    (50 jobs) and ta111 (500x20, the reference's largest class) must run
    through the resident engine untouched. The reference needs a rebuild
    with larger `config param MAX_JOBS` beyond 20 jobs
    (`PFSP_node.chpl:7`, SURVEY.md §5 long-context note)."""
    from tpu_tree_search.problems import PFSPProblem

    res = resident_search(
        PFSPProblem(inst=31, lb="lb1", ub=1), m=25, M=2048, K=2, max_steps=2
    )
    assert res.explored_tree > 0
    assert not res.complete

    res = resident_search(
        PFSPProblem(inst=111, lb="lb1_d", ub=1), m=25, M=128, K=2, max_steps=1
    )
    assert res.explored_tree > 0



def test_lb2_staged_end_to_end_parity(monkeypatch):
    """TTS_LB2_STAGED=1 forces the staged evaluator (lb1 prefilter ->
    compacted self-lb2) on CPU; tree/sol/best must match the single-pass
    lb2 run node-for-node — staging is an exact work reduction, not an
    approximation. Fresh problem objects per mode (resident programs cache
    on the instance and the knob is read at build time)."""
    ptm = taillard.reduced_instance(14, jobs=10, machines=5)
    opt = sequential_search(PFSPProblem(lb="lb2", ub=0, p_times=ptm)).best

    monkeypatch.setenv("TTS_LB2_STAGED", "0")
    base = resident_search(
        PFSPProblem(lb="lb2", ub=0, p_times=ptm), m=8, M=256, K=8,
        initial_best=opt,
    )
    monkeypatch.setenv("TTS_LB2_STAGED", "1")
    staged = resident_search(
        PFSPProblem(lb="lb2", ub=0, p_times=ptm), m=8, M=256, K=8,
        initial_best=opt,
    )
    assert (staged.explored_tree, staged.explored_sol, staged.best) == (
        base.explored_tree, base.explored_sol, base.best
    )

    # Improving-incumbent mode too (best changes mid-run, so the candidate
    # mask shifts cycle to cycle).
    monkeypatch.setenv("TTS_LB2_STAGED", "0")
    base2 = resident_search(
        PFSPProblem(lb="lb2", ub=0, p_times=ptm), m=8, M=256, K=8
    )
    monkeypatch.setenv("TTS_LB2_STAGED", "1")
    staged2 = resident_search(
        PFSPProblem(lb="lb2", ub=0, p_times=ptm), m=8, M=256, K=8
    )
    assert (staged2.explored_tree, staged2.explored_sol, staged2.best) == (
        base2.explored_tree, base2.explored_sol, base2.best
    )
    assert staged2.best == opt


def test_staged_knob_flip_rebuilds_program_same_instance(monkeypatch):
    """Flipping TTS_LB2_STAGED between searches on the SAME problem
    instance must rebuild the compiled program, not silently reuse the
    stale one — the staged decision is baked in at trace time, so the
    cache key must carry it (round-5 fix)."""
    from tpu_tree_search.problems.pfsp import taillard

    ptm = taillard.reduced_instance(14, jobs=8, machines=5)
    prob = PFSPProblem(lb="lb2", ub=0, p_times=ptm)
    opt = sequential_search(PFSPProblem(lb="lb2", ub=0, p_times=ptm)).best

    # Pin a fixed K: under TTS_K=auto (the tests-pipeline CI job) one
    # search builds a program per ladder rung, which would break this
    # test's exact program-count arithmetic without testing its claim.
    monkeypatch.delenv("TTS_K", raising=False)
    monkeypatch.setenv("TTS_LB2_STAGED", "1")
    r1 = resident_search(prob, m=8, M=128, K=8, initial_best=opt)
    n_after_first = len(prob._resident_programs)
    monkeypatch.setenv("TTS_LB2_STAGED", "0")
    r2 = resident_search(prob, m=8, M=128, K=8, initial_best=opt)
    assert len(prob._resident_programs) == n_after_first + 1, (
        "knob flip reused the stale staged program"
    )
    assert (r1.explored_tree, r1.explored_sol, r1.best) == (
        r2.explored_tree, r2.explored_sol, r2.best
    )
    # The lb2-family kill switch must also rebuild — even when staging is
    # FORCED (=1), so the staged decision alone cannot distinguish the
    # configs (code-review r5: the kill switch silently failing to take
    # effect on same-instance reuse would keep a failing Pallas kernel
    # live).
    monkeypatch.setenv("TTS_LB2_STAGED", "1")
    resident_search(prob, m=8, M=128, K=8, initial_best=opt)
    n_before_kill = len(prob._resident_programs)
    monkeypatch.setenv("TTS_PALLAS_LB2", "0")
    r3 = resident_search(prob, m=8, M=128, K=8, initial_best=opt)
    assert len(prob._resident_programs) == n_before_kill + 1, (
        "TTS_PALLAS_LB2 flip reused the stale program"
    )
    assert (r3.explored_tree, r3.explored_sol, r3.best) == (
        r1.explored_tree, r1.explored_sol, r1.best
    )


def test_compact_ids_sort_matches_scatter(monkeypatch):
    """The two compaction implementations (TTS_COMPACT) must return
    IDENTICAL ids for every live position — same survivors, same
    (parent, slot) order — across dense, sparse, empty, and full masks."""
    import numpy as np

    from tpu_tree_search.engine.resident import _compact_ids

    rng = np.random.default_rng(3)
    cases = [
        rng.random((64, 20)) < p for p in (0.0, 0.03, 0.35, 1.0)
    ] + [np.zeros((1, 7), bool), np.ones((5, 3), bool)]
    for keep in cases:
        S = keep.size  # full budget: exercises every survivor position
        monkeypatch.setenv("TTS_COMPACT", "scatter")
        ids_sc, inc_sc = (np.asarray(x) for x in _compact_ids(keep, S))
        for mode in ("sort", "search", "dense"):
            monkeypatch.setenv("TTS_COMPACT", mode)
            ids_x, inc_x = (np.asarray(x) for x in _compact_ids(keep, S))
            assert inc_sc == inc_x == keep.sum(), mode
            np.testing.assert_array_equal(ids_sc[:inc_sc], ids_x[:inc_x])


def test_compact_knob_parity_end_to_end(monkeypatch):
    """A full resident search under each TTS_COMPACT mode hits the same
    exact counts (fresh problem per mode: programs cache on the instance,
    keyed by the routing token that includes the knob)."""
    ptm = taillard.reduced_instance(14, jobs=9, machines=5)
    opt = sequential_search(PFSPProblem(lb="lb1", ub=0, p_times=ptm)).best
    results = {}
    for mode in ("scatter", "sort", "search", "dense"):
        monkeypatch.setenv("TTS_COMPACT", mode)
        res = resident_search(
            PFSPProblem(lb="lb1", ub=0, p_times=ptm), m=8, M=128, K=32,
            initial_best=opt,
        )
        results[mode] = (res.explored_tree, res.explored_sol, res.best)
    assert (results["scatter"] == results["sort"] == results["search"]
            == results["dense"])


def test_compact_knob_flip_rebuilds_program_same_instance(monkeypatch):
    """Flipping TTS_COMPACT between searches on ONE problem instance must
    rebuild the resident program (the knob is part of the routing token),
    not silently reuse the stale compaction."""
    prob = NQueensProblem(N=9)
    seq = sequential_search(prob)
    monkeypatch.setenv("TTS_COMPACT", "scatter")
    r1 = resident_search(prob, m=8, M=128, K=32)
    monkeypatch.setenv("TTS_COMPACT", "sort")
    r2 = resident_search(prob, m=8, M=128, K=32)
    assert (r1.explored_tree, r1.explored_sol) == (
        seq.explored_tree, seq.explored_sol)
    assert (r2.explored_tree, r2.explored_sol) == (
        seq.explored_tree, seq.explored_sol)
