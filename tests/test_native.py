"""Native (C++) host runtime vs Python oracle parity.

The C++ library (`csrc/tts_native.cpp`) must reproduce the Python engines'
counts and traversal *order* exactly: the distributed tier's static partition
slices the warm-up frontier positionally, so even frontier ordering is a
semantic contract, not an implementation detail (SURVEY.md Appendix A).
"""

import numpy as np
import pytest

from tpu_tree_search import native
from tpu_tree_search.engine import sequential_search
from tpu_tree_search.engine.device import drain, warmup
from tpu_tree_search.pool import SoAPool
from tpu_tree_search.problems import NQueensProblem, PFSPProblem
from tpu_tree_search.problems.base import INF_BOUND, index_batch
from tpu_tree_search.problems.pfsp import taillard as T

if native.load() is None:
    pytest.skip(
        f"native library unavailable: {native.load_error()}",
        allow_module_level=True,
    )


def _python_only(problem):
    """Return the same problem with the native runtime disabled."""
    problem._native_rt = None
    return problem


def _seed_pool(problem):
    pool = SoAPool(problem.node_fields())
    pool.push_back(index_batch(problem.root(), 0))
    return pool


# -- sequential tier ---------------------------------------------------------


@pytest.mark.parametrize("n", [6, 8, 9])
def test_nqueens_sequential_parity(n):
    res_nat = sequential_search(NQueensProblem(N=n))
    res_py = sequential_search(_python_only(NQueensProblem(N=n)))
    assert (res_nat.explored_tree, res_nat.explored_sol) == (
        res_py.explored_tree,
        res_py.explored_sol,
    )


@pytest.mark.parametrize("lb", ["lb1", "lb1_d", "lb2"])
@pytest.mark.parametrize("ub_seed", [False, True])
def test_pfsp_sequential_parity(lb, ub_seed):
    """ub=0 (evolving incumbent) is the strong test: any traversal-order
    difference changes the explored tree."""
    ptm = T.reduced_instance(14, jobs=7, machines=5)

    def run(problem):
        best = 1_000_000 if ub_seed else None
        return sequential_search(problem, initial_best=best)

    res_nat = run(PFSPProblem(lb=lb, ub=0, p_times=ptm))
    res_py = run(_python_only(PFSPProblem(lb=lb, ub=0, p_times=ptm)))
    assert (res_nat.explored_tree, res_nat.explored_sol, res_nat.best) == (
        res_py.explored_tree,
        res_py.explored_sol,
        res_py.best,
    )


# -- warm-up / drain phases --------------------------------------------------


def test_nqueens_warmup_frontier_identical():
    target = 50
    p_nat = NQueensProblem(N=9)
    p_py = _python_only(NQueensProblem(N=9))
    pool_nat, pool_py = _seed_pool(p_nat), _seed_pool(p_py)
    out_nat = warmup(p_nat, pool_nat, INF_BOUND, target)
    out_py = warmup(p_py, pool_py, INF_BOUND, target)
    assert out_nat == out_py
    b_nat, b_py = pool_nat.as_batch(), pool_py.as_batch()
    assert pool_nat.size == pool_py.size
    np.testing.assert_array_equal(b_nat["depth"], b_py["depth"])
    np.testing.assert_array_equal(b_nat["board"], b_py["board"])


@pytest.mark.parametrize("lb", ["lb1", "lb2"])
def test_pfsp_warmup_frontier_identical(lb):
    ptm = T.reduced_instance(3, jobs=8, machines=5)
    target = 60
    p_nat = PFSPProblem(lb=lb, ub=0, p_times=ptm)
    p_py = _python_only(PFSPProblem(lb=lb, ub=0, p_times=ptm))
    pool_nat, pool_py = _seed_pool(p_nat), _seed_pool(p_py)
    out_nat = warmup(p_nat, pool_nat, INF_BOUND, target)
    out_py = warmup(p_py, pool_py, INF_BOUND, target)
    assert out_nat == out_py
    b_nat, b_py = pool_nat.as_batch(), pool_py.as_batch()
    for field in ("depth", "limit1", "prmu"):
        np.testing.assert_array_equal(b_nat[field], b_py[field])


def test_pfsp_drain_parity():
    ptm = T.reduced_instance(5, jobs=8, machines=5)
    p_nat = PFSPProblem(lb="lb1", ub=0, p_times=ptm)
    p_py = _python_only(PFSPProblem(lb="lb1", ub=0, p_times=ptm))
    pool_nat, pool_py = _seed_pool(p_nat), _seed_pool(p_py)
    warmup(p_nat, pool_nat, INF_BOUND, 40)
    warmup(p_py, pool_py, INF_BOUND, 40)
    out_nat = drain(p_nat, pool_nat, INF_BOUND)
    out_py = drain(p_py, pool_py, INF_BOUND)
    assert out_nat == out_py
    assert pool_nat.size == 0


# -- generate_children (device-result consumption) ---------------------------


def _random_pfsp_parents(rng, jobs, count):
    prmu = np.tile(np.arange(jobs, dtype=np.int32), (count, 1))
    for row in prmu:
        rng.shuffle(row)
    limit1 = rng.integers(-1, jobs - 1, size=count).astype(np.int32)
    depth = (limit1 + 1).astype(np.int32)
    return {"depth": depth, "limit1": limit1, "prmu": prmu}


def test_pfsp_generate_children_parity():
    rng = np.random.default_rng(7)
    jobs = 9
    ptm = T.reduced_instance(2, jobs=jobs, machines=4)
    p_nat = PFSPProblem(lb="lb1", ub=0, p_times=ptm)
    p_py = _python_only(PFSPProblem(lb="lb1", ub=0, p_times=ptm))
    for _ in range(20):
        count = int(rng.integers(1, 40))
        parents = _random_pfsp_parents(rng, jobs, count)
        bounds = rng.integers(0, 2000, size=(count, jobs)).astype(np.int32)
        best = int(rng.integers(500, 1500))
        r_nat = p_nat.generate_children(parents, count, bounds, best)
        r_py = p_py.generate_children(parents, count, bounds, best)
        assert (r_nat.tree_inc, r_nat.sol_inc, r_nat.best) == (
            r_py.tree_inc,
            r_py.sol_inc,
            r_py.best,
        )
        for field in ("depth", "limit1", "prmu"):
            np.testing.assert_array_equal(
                r_nat.children[field], r_py.children[field]
            )


def test_nqueens_generate_children_parity():
    rng = np.random.default_rng(3)
    N = 8
    p_nat = NQueensProblem(N=N)
    p_py = _python_only(NQueensProblem(N=N))
    for _ in range(20):
        count = int(rng.integers(1, 30))
        boards = np.tile(np.arange(N, dtype=np.uint8), (count, 1))
        for row in boards:
            rng.shuffle(row)
        depth = rng.integers(0, N + 1, size=count).astype(np.int32)
        parents = {"depth": depth, "board": boards}
        labels = rng.integers(0, 2, size=(count, N)).astype(np.uint8)
        r_nat = p_nat.generate_children(parents, count, labels, INF_BOUND)
        r_py = p_py.generate_children(parents, count, labels, INF_BOUND)
        assert (r_nat.tree_inc, r_nat.sol_inc) == (r_py.tree_inc, r_py.sol_inc)
        for field in ("depth", "board"):
            np.testing.assert_array_equal(
                r_nat.children[field], r_py.children[field]
            )


# -- full offload tier with the native host path -----------------------------


def test_device_search_native_matches_sequential():
    from tpu_tree_search.engine.device import device_search

    prob = NQueensProblem(N=9)
    seq = sequential_search(NQueensProblem(N=9))
    res = device_search(prob, m=8, M=512)
    assert (res.explored_tree, res.explored_sol) == (
        seq.explored_tree,
        seq.explored_sol,
    )
