"""Flight recorder + live monitor (obs/flightrec.py, obs/live.py):
SIGTERM dump validity, ring-buffer bounds, disabled-mode cost, guard
interaction, and the --obs-serve/tts watch HTTP surface."""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from tpu_tree_search.obs import events, flightrec
from tpu_tree_search.obs.flightrec import FlightRecorder
from tpu_tree_search.problems import NQueensProblem


@pytest.fixture(autouse=True)
def _clean_recorder():
    rec = flightrec.recorder()
    period = rec._snap_period_us
    flightrec.reset()
    yield
    rec._snap_period_us = period  # tests drop the rate limit; restore it
    flightrec.reset()


# -- enablement + ring bounds ------------------------------------------------


def test_disabled_heartbeat_records_nothing(monkeypatch):
    monkeypatch.delenv("TTS_OBS", raising=False)
    monkeypatch.delenv("TTS_FLIGHTREC", raising=False)
    assert not flightrec.enabled()
    flightrec.heartbeat("resident", seq=1, cycles=2, size=10, best=5)
    assert flightrec.latest() is None
    assert flightrec.recorder().state()["last_dispatch"] == {}
    # TTS_FLIGHTREC=0 force-disables even with obs on.
    monkeypatch.setenv("TTS_OBS", "host")
    monkeypatch.setenv("TTS_FLIGHTREC", "0")
    assert not flightrec.enabled()
    # An explicit prefix arms recording without TTS_OBS.
    monkeypatch.delenv("TTS_OBS", raising=False)
    monkeypatch.setenv("TTS_FLIGHTREC", "/tmp/x")
    assert flightrec.enabled()
    assert flightrec.dump_prefix() == "/tmp/x"


def test_ring_buffer_bounded(monkeypatch):
    monkeypatch.setenv("TTS_OBS", "host")
    rec = FlightRecorder(ring=8, snapshot_period_us=0.0)
    for i in range(100):
        rec.heartbeat("resident", seq=i + 1, cycles=1, size=i,
                      best=100, tree=i * 10, sol=0)
    snaps = rec.snapshots()
    assert len(snaps) == 8  # bounded: oldest aged out
    assert snaps[-1]["seq"] == 100 and snaps[0]["seq"] >= 92
    assert rec.latest()["tree"] == 990


def test_snapshot_rate_limit_and_aggregation(monkeypatch):
    monkeypatch.setenv("TTS_OBS", "host")
    rec = FlightRecorder(snapshot_period_us=1e12)  # one snapshot ever
    rec.heartbeat("multi", host=0, wid=0, seq=3, size=10, best=9,
                  tree=100, sol=1, steals=2)
    rec.heartbeat("multi", host=0, wid=1, seq=5, size=20, best=7,
                  tree=50, sol=0, steals=1)
    rec.set_idle(0, 1, True)
    state = rec.state()
    assert set(state["last_dispatch"]) == {"h0/w0", "h0/w1"}
    assert state["idle_workers"] == ["h0/w1"]
    # Only the first heartbeat could snapshot (rate limit).
    assert len(rec.snapshots()) == 1
    # A fresh recorder with no limit aggregates across workers.
    rec2 = FlightRecorder(snapshot_period_us=0.0)
    rec2.heartbeat("multi", wid=0, seq=1, size=10, best=9, tree=100,
                   sol=1, steals=2)
    rec2.heartbeat("multi", wid=1, seq=2, size=20, best=7, tree=50,
                   sol=0, steals=1)
    snap = rec2.latest()
    assert snap["tree"] == 150 and snap["best"] == 7
    assert snap["size"] == 30 and snap["steals"] == 3
    assert snap["workers"] == 2


def test_heartbeats_ride_resident_dispatch_boundaries(monkeypatch):
    from tpu_tree_search.engine.resident import resident_search

    monkeypatch.setenv("TTS_OBS", "host")
    events.reset()
    res = resident_search(NQueensProblem(N=9), m=8, M=128, K=4)
    state = flightrec.recorder().state()
    last = state["last_dispatch"]["h0/w0"]
    # The registry names the last completed dispatch: the final one is the
    # terminal (or drained speculative) dispatch of a finished search.
    assert last["seq"] >= 2
    assert last["tree"] + res.phases[0].tree + res.phases[2].tree \
        == res.explored_tree
    assert state["meta"]["tier"] == "resident"
    # Rate-limited snapshot counter samples landed in the event stream.
    names = {e["name"] for e in events.drain()}
    assert "snapshot" in names


# -- dump validity -----------------------------------------------------------


def test_dump_writes_parseable_trace_and_metrics(tmp_path, monkeypatch):
    from tpu_tree_search.engine.resident import resident_search
    from tpu_tree_search.obs.export import load_trace
    from tpu_tree_search.obs.report import summarize

    monkeypatch.setenv("TTS_OBS", "host")
    events.reset()
    resident_search(NQueensProblem(N=9), m=8, M=128, K=4)
    prefix = str(tmp_path / "fr")
    path = flightrec.dump("unit-test", prefix=prefix)
    assert path == prefix + ".trace.json"
    obj = json.loads((tmp_path / "fr.trace.json").read_text())
    frd = obj["otherData"]["flightrec"]
    assert frd["reason"] == "unit-test"
    assert "h0/w0" in frd["last_dispatch"]
    assert {"seq", "cycles", "size", "inflight"} <= set(
        frd["last_dispatch"]["h0/w0"]
    )
    # The dump is a VALID trace: loadable + summarizable like any other.
    evts = load_trace(str(tmp_path / "fr.trace.json"))
    s = summarize(evts)
    assert s["events"] > 0 and s["cycle_rate"]
    lines = (tmp_path / "fr.metrics.jsonl").read_text().splitlines()
    recs = [json.loads(ln) for ln in lines]
    assert any(r.get("name") == "snapshot" for r in recs)


def test_sigterm_mid_search_leaves_postmortem(tmp_path):
    """The acceptance criterion: a CPU run killed mid-search (SIGTERM)
    leaves a parseable Chrome-trace + metrics dump identifying the last
    completed dispatch."""
    prefix = str(tmp_path / "killed")
    env = dict(
        os.environ, JAX_PLATFORMS="cpu", TTS_OBS="host",
        TTS_FLIGHTREC=prefix,
    )
    # N=15 runs for minutes on CPU — the kill always lands mid-search.
    proc = subprocess.Popen(
        [sys.executable, "-m", "tpu_tree_search.cli", "nqueens",
         "--N", "15", "--tier", "device", "--M", "4096", "--K", "16"],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    try:
        time.sleep(20)  # past compile, into the dispatch loop
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
    assert rc == -signal.SIGTERM  # honest death status preserved
    obj = json.loads((tmp_path / "killed.trace.json").read_text())
    frd = obj["otherData"]["flightrec"]
    assert frd["reason"] == "SIGTERM"
    last = frd["last_dispatch"]["h0/w0"]
    assert last["seq"] >= 1 and last["tree"] > 0
    assert "idle_workers" in frd and "meta" in frd
    # tts report consumes the corpse like any trace (exit 0).
    from tpu_tree_search import cli

    assert cli.main(["report", prefix + ".trace.json",
                     prefix + ".metrics.jsonl"]) == 0


def test_dump_never_raises(tmp_path):
    # Unwritable prefix: dump returns None instead of raising (a failed
    # post-mortem must not change how the process dies).
    assert flightrec.dump("x", prefix=str(tmp_path / "no/such/dir/p")) is None


def test_excepthook_dumps_then_chains(tmp_path, monkeypatch):
    monkeypatch.setenv("TTS_OBS", "host")
    monkeypatch.setenv("TTS_FLIGHTREC", str(tmp_path / "exc"))
    rec = FlightRecorder(snapshot_period_us=0.0)
    rec.heartbeat("resident", seq=1, cycles=1, size=5, best=3, tree=10,
                  sol=0)
    called = {}
    rec._prev_excepthook = lambda *a: called.setdefault("prev", a)
    try:
        raise ValueError("boom")
    except ValueError:
        rec._on_exception(*sys.exc_info())
    assert called["prev"][0] is ValueError
    obj = json.loads((tmp_path / "exc.trace.json").read_text())
    assert obj["otherData"]["flightrec"]["reason"].startswith(
        "exception: ValueError"
    )


# -- guard + disabled-path interaction --------------------------------------


def test_guarded_run_green_with_flightrec_armed(tmp_path, monkeypatch):
    """TTS_GUARD=1 + TTS_OBS=1 + flight recording: heartbeats are pure
    host bookkeeping at existing dispatch boundaries — zero recompiles,
    zero implicit transfers, counts unchanged."""
    from tpu_tree_search.engine.resident import resident_search
    from tpu_tree_search.engine.sequential import sequential_search

    monkeypatch.setenv("TTS_OBS", "1")
    monkeypatch.setenv("TTS_FLIGHTREC", str(tmp_path / "g"))
    events.reset()
    res = resident_search(NQueensProblem(N=9), m=8, M=128, K=4, guard=True)
    seq = sequential_search(NQueensProblem(N=9))
    assert (res.explored_tree, res.explored_sol) == (
        seq.explored_tree, seq.explored_sol
    )
    assert flightrec.latest() is not None


# -- live monitor (obs/live.py) ----------------------------------------------


@pytest.fixture()
def live_server(monkeypatch):
    from tpu_tree_search.obs import live

    monkeypatch.setenv("TTS_OBS", "host")
    srv = live.serve(0)  # ephemeral port
    yield srv
    srv.close()


def _feed(n: int = 3):
    rec = flightrec.recorder()
    for i in range(n):
        rec.heartbeat("resident", seq=i + 1, cycles=4, size=100 + i,
                      best=1377, tree=1000 * (i + 1), sol=3, depth=2, K=16)


def test_live_endpoints(live_server):
    from urllib.request import urlopen

    base = live_server.url
    with urlopen(base + "/snapshot", timeout=5) as r:
        assert json.loads(r.read()) == {}  # before any heartbeat
    flightrec.recorder()._snap_period_us = 0.0
    _feed(3)
    with urlopen(base + "/snapshot", timeout=5) as r:
        snap = json.loads(r.read())
    assert snap["seq"] == 3 and snap["best"] == 1377 and snap["K"] == 16
    with urlopen(base + "/snapshots?n=2", timeout=5) as r:
        assert len(json.loads(r.read())) == 2
    with urlopen(base + "/state", timeout=5) as r:
        state = json.loads(r.read())
    assert "h0/w0" in state["last_dispatch"]
    with urlopen(base + "/healthz", timeout=5) as r:
        assert json.loads(r.read()) == {"ok": True}


def test_live_sse_stream_and_watch(live_server, capsys):
    from urllib.request import urlopen

    from tpu_tree_search.obs.live import format_snapshot, watch_main

    flightrec.recorder()._snap_period_us = 0.0
    _feed(2)
    with urlopen(live_server.url + "/stream", timeout=10) as resp:
        snap = None
        for raw in resp:
            line = raw.decode().strip()
            if line.startswith("data: "):
                snap = json.loads(line[6:])
                break
    assert snap is not None and snap["seq"] == 2
    # The watch client renders the streamed snapshot.
    assert watch_main(live_server.port, once=True) == 0
    out = capsys.readouterr().out
    assert "best=1377" in out and "K=16" in out
    assert watch_main(live_server.port, max_updates=1, as_json=True) == 0
    streamed = json.loads(capsys.readouterr().out.strip())
    assert streamed["seq"] == 2
    line = format_snapshot(snap)
    assert "nodes/s" in line and "dispatch#2" in line


def test_watch_unreachable_exits_2(capsys):
    from tpu_tree_search.obs.live import watch_main

    # A closed ephemeral port: grab one, close it, then watch it.
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    assert watch_main(port, once=True) == 2
    assert "no live monitor" in capsys.readouterr().err


def test_cli_obs_serve_flag(monkeypatch, capsys):
    """--obs-serve runs a search with the monitor up and implies TTS_OBS;
    the search result is unchanged."""
    from tpu_tree_search import cli

    monkeypatch.delenv("TTS_OBS", raising=False)
    # Port 0 => ephemeral: proves the flag path end to end without racing
    # a fixed port against parallel CI jobs.
    assert cli.main([
        "nqueens", "--N", "8", "--tier", "device", "--m", "5", "--M", "64",
        "--obs-serve", "0", "--json",
    ]) == 0
    out = capsys.readouterr().out
    assert "Live monitor: http://127.0.0.1:" in out
    rec = json.loads(out.strip().splitlines()[-1])
    assert rec["explored_sol"] == 92
