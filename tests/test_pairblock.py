"""Pair-blocked lb2 (TTS_LB2_PAIRBLOCK) and the lb2 variant enum.

The Johnson machine-pair axis is evaluated in blocks of ``Pb`` pairs as an
extra tensor axis (`ops/pfsp_device._lb2_chunk` / `_lb2_self_chunk`) instead
of the reference's serial per-pair loop (`Bound_johnson.chpl:188-239`).
Blocking must be bit-exact against the serial path and the numpy oracle for
every block size — including the degenerate ``Pb=1`` (old behavior) and
``Pb=P`` — at ta014-class (P=45) and ta021-class (20x20, P=190) shapes,
across jnp and Pallas-interpret, under every lb2 variant; the blocked
compiled program must contain no per-pair serial loop; and the resolved
block size must key the program caches.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pytest

from tpu_tree_search.engine.resident import resident_search
from tpu_tree_search.engine.sequential import sequential_search
from tpu_tree_search.ops import pallas_kernels as PK
from tpu_tree_search.ops import pfsp_device as P
from tpu_tree_search.problems import PFSPProblem
from tpu_tree_search.problems.pfsp import bounds as B
from tpu_tree_search.problems.pfsp import taillard


def _random_nodes(rng, jobs, count, min_limit1=-1):
    prmu = np.stack([rng.permutation(jobs).astype(np.int32)
                     for _ in range(count)])
    limit1 = rng.integers(min_limit1, jobs - 1, count).astype(np.int32)
    return prmu, limit1


def _tables(prob):
    return P.PFSPDeviceTables(prob.lb1_data, prob.lb2_data)


# ta014 class (20x10, P=45) and ta021 class (20x20, P=190 — the published
# lb2 target config, `pfsp_multigpu_chpl.chpl:312`).
SHAPES = [pytest.param(14, id="ta014-P45"), pytest.param(21, id="ta021-P190")]


@pytest.mark.parametrize("inst", SHAPES)
@pytest.mark.parametrize("variant", ["full", "nabeshima", "lageweg"])
def test_lb2_chunk_pairblock_bit_exact(inst, variant):
    """Blocked child bounds == serial child bounds == numpy oracle, for
    Pb in {1, 8, P} (and a non-divisor to exercise block padding)."""
    rng = np.random.default_rng(7 + inst)
    prob = PFSPProblem(inst=inst, lb="lb2", ub=1, lb2_variant=variant)
    t = _tables(prob)
    n, Pn = prob.jobs, t.pairs.shape[0]
    Bsz = 32
    prmu, limit1 = _random_nodes(rng, n, Bsz)
    pd, ld = jnp.asarray(prmu), jnp.asarray(limit1)
    serial = np.asarray(P._lb2_chunk(
        pd, ld, t.ptm_t, t.min_heads, t.min_tails,
        t.pairs, t.lags, t.johnson_schedules, pairblock=1,
    ))
    open_ = np.arange(n)[None, :] >= limit1[:, None] + 1
    for pb in {1, 7, 8, Pn, Pn + 5}:
        got = np.asarray(P._lb2_chunk(
            pd, ld, t.ptm_t, t.min_heads, t.min_tails,
            t.pairs, t.lags, t.johnson_schedules, pairblock=pb,
        ))
        assert np.array_equal(serial[open_], got[open_]), (variant, pb)
    # Numpy oracle on a few children (full bound, no early exit).
    big = 10**9
    for i in range(4):
        li = int(limit1[i])
        for k in range(li + 1, n):
            child = prmu[i].copy()
            child[li + 1], child[k] = child[k], child[li + 1]
            want = B.lb2_bound(prob.lb1_data, prob.lb2_data, child,
                               li + 1, n, big)
            assert serial[i, k] == want, (variant, i, k)


@pytest.mark.parametrize("inst", SHAPES)
def test_lb2_self_chunk_pairblock_bit_exact(inst):
    rng = np.random.default_rng(11 + inst)
    prob = PFSPProblem(inst=inst, lb="lb2", ub=1)
    t = _tables(prob)
    n, Pn = prob.jobs, t.pairs.shape[0]
    prmu, limit1 = _random_nodes(rng, n, 32, min_limit1=0)
    pd, ld = jnp.asarray(prmu), jnp.asarray(limit1)
    serial = np.asarray(P._lb2_self_chunk(
        pd, ld, t.ptm_t, t.min_heads, t.min_tails,
        t.pairs, t.lags, t.johnson_schedules, pairblock=1,
    ))
    for pb in {8, Pn}:
        got = np.asarray(P._lb2_self_chunk(
            pd, ld, t.ptm_t, t.min_heads, t.min_tails,
            t.pairs, t.lags, t.johnson_schedules, pairblock=pb,
        ))
        assert np.array_equal(serial, got), pb
    # Oracle: self bound of a row == lb2_bound of the node itself.
    big = 10**9
    for i in range(6):
        want = B.lb2_bound(prob.lb1_data, prob.lb2_data, prmu[i],
                           int(limit1[i]), n, big)
        assert serial[i] == want, i


@pytest.mark.parametrize("pg", [1, 4, 8])
def test_pallas_kernels_pair_group_parity_at_P190(pg):
    """Pallas child + staged-self kernels with pair-group unrolling, at the
    published ta021 shape (P=190 — pg divides and doesn't divide it),
    interpret mode, vs the jnp oracles."""
    rng = np.random.default_rng(23)
    prob = PFSPProblem(inst=21, lb="lb2", ub=1)
    t = _tables(prob)
    n = prob.jobs
    prmu, limit1 = _random_nodes(rng, n, 32)
    pd, ld = jnp.asarray(prmu), jnp.asarray(limit1)
    oracle = np.asarray(P._lb2_chunk(
        pd, ld, t.ptm_t, t.min_heads, t.min_tails,
        t.pairs, t.lags, t.johnson_schedules,
    ))
    got = np.asarray(PK.pfsp_lb2_bounds(pd, ld, t, interpret=True,
                                        pair_group=pg))
    open_ = np.arange(n)[None, :] >= limit1[:, None] + 1
    assert np.array_equal(oracle[open_], got[open_])
    # Self kernel on rows with limit1 >= 0 (staged contract).
    prmu2, limit2 = _random_nodes(rng, n, 24, min_limit1=0)
    p2, l2 = jnp.asarray(prmu2), jnp.asarray(limit2)
    self_oracle = np.asarray(P._lb2_self_chunk(
        p2, l2, t.ptm_t, t.min_heads, t.min_tails,
        t.pairs, t.lags, t.johnson_schedules,
    ))
    self_got = np.asarray(PK.pfsp_lb2_self_bounds(
        p2, l2, 24, t, interpret=True, pair_group=pg,
    ))
    assert np.array_equal(self_oracle, self_got)


def test_blocked_jaxpr_has_no_per_pair_loop():
    """The pinned structural claim — routed through the contract registry
    (`lb2-pairblock-loop-free`, declared in ops/pfsp_device.py, ISSUE 8):
    with blocking on, the compiled lb2 child/self evaluators contain NO
    fori_loop whose trip count scales with P — the only loop left is
    `_parent_state`'s O(n) prefix scan.  The serial build (Pb=1) keeps its
    pair loop, so the count isn't trivially zero-by-construction; the
    audit traces both at the ta021 shape (P=190, where auto genuinely
    blocks)."""
    from tpu_tree_search.analysis import program_audit

    program_audit.load_contracts()
    # Serial (Pb=1, non-vacuity arm) + the auto resolution; the explicit
    # mid-size block rides the full-matrix `tts check` CI job.
    findings = program_audit.audit_lb2_eval(pairblocks=(1, None))
    assert findings == [], [f.render() for f in findings]


def test_pairblock_keys_routing_token_and_rebuilds_program(monkeypatch):
    """Flipping TTS_LB2_PAIRBLOCK between searches on ONE problem instance
    must change `routing_cache_token` and rebuild the resident program —
    the block size is baked in at trace time — and both builds must land
    the same exact counts."""
    ptm = taillard.reduced_instance(14, jobs=8, machines=5)
    prob = PFSPProblem(lb="lb2", ub=0, p_times=ptm)
    opt = sequential_search(PFSPProblem(lb="lb2", ub=0, p_times=ptm)).best

    monkeypatch.setenv("TTS_LB2_PAIRBLOCK", "1")
    tok1 = P.routing_cache_token(prob)
    monkeypatch.setenv("TTS_LB2_PAIRBLOCK", "4")
    tok4 = P.routing_cache_token(prob)
    monkeypatch.setenv("TTS_LB2_PAIRBLOCK", "auto")
    tok_auto = P.routing_cache_token(prob)  # resolves to P=10 here
    assert len({tok1, tok4, tok_auto}) == 3

    monkeypatch.setenv("TTS_LB2_PAIRBLOCK", "1")
    r1 = resident_search(prob, m=8, M=128, K=8, initial_best=opt)
    n_first = len(prob._resident_programs)
    monkeypatch.setenv("TTS_LB2_PAIRBLOCK", "4")
    r2 = resident_search(prob, m=8, M=128, K=8, initial_best=opt)
    assert len(prob._resident_programs) == n_first + 1, (
        "pairblock flip reused the stale program"
    )
    assert (r1.explored_tree, r1.explored_sol, r1.best) == (
        r2.explored_tree, r2.explored_sol, r2.best
    )


def test_pairblock_knob_validation(monkeypatch):
    monkeypatch.setenv("TTS_LB2_PAIRBLOCK", "0")
    with pytest.raises(ValueError, match="must be >= 1"):
        P.lb2_pairblock(45, 20)
    monkeypatch.setenv("TTS_LB2_PAIRBLOCK", "fast")
    with pytest.raises(ValueError, match="'auto' or a positive integer"):
        P.lb2_pairblock(45, 20)
    monkeypatch.setenv("TTS_LB2_PAIRBLOCK", "512")
    assert P.lb2_pairblock(45, 20) == 45  # clamped to P
    monkeypatch.delenv("TTS_LB2_PAIRBLOCK", raising=False)
    assert P.lb2_pairblock(45, 20) == 45   # auto: single block at ta014
    assert P.lb2_pairblock(190, 20) == 64  # auto: 3 blocks at ta021
    assert P.lb2_pairblock(190, 500) == 4  # auto shrinks with job count
    assert P.lb2_kernel_pair_group(190, 20) == 8  # kernel unroll cap


# -- lb2 variant enum (`Bound_johnson.chpl:50-88`) --------------------------


def test_variant_pair_sets_hand_checked():
    """`fill_machine_pairs` equivalents at m=4, against hand-written sets."""
    assert B.machine_pairs(4, "full") == [
        (0, 1), (0, 2), (0, 3), (1, 2), (1, 3), (2, 3)
    ]
    assert B.machine_pairs(4, "nabeshima") == [(0, 1), (1, 2), (2, 3)]
    assert B.machine_pairs(4, "lageweg") == [(0, 3), (1, 3), (2, 3)]
    # Counts at the published 20-machine shape.
    assert len(B.machine_pairs(20, "full")) == 190
    assert len(B.machine_pairs(20, "nabeshima")) == 19
    assert len(B.machine_pairs(20, "lageweg")) == 19
    with pytest.raises(ValueError, match="lb2_variant"):
        B.machine_pairs(4, "learn")


@pytest.mark.parametrize("variant", ["nabeshima", "lageweg"])
def test_variant_bounds_are_valid_and_dominated_by_full(variant):
    """A pair-subset bound is (a) a valid lower bound on every completion
    and (b) pointwise <= the full-variant bound (max over a subset)."""
    ptm = taillard.reduced_instance(21, jobs=8, machines=6)
    d1 = B.make_lb1(ptm)
    d2_full = B.make_lb2(d1, "full")
    d2_sub = B.make_lb2(d1, variant)
    rng = np.random.default_rng(17)
    big = 10**9
    for _ in range(25):
        prmu = rng.permutation(8).astype(np.int32)
        limit1 = int(rng.integers(-1, 7))
        sub = B.lb2_bound(d1, d2_sub, prmu, limit1, 8, big)
        full = B.lb2_bound(d1, d2_full, prmu, limit1, 8, big)
        assert sub <= full
        for _ in range(4):
            tail = prmu[limit1 + 1:].copy()
            rng.shuffle(tail)
            whole = np.concatenate([prmu[: limit1 + 1], tail])
            assert B.eval_solution(d1, whole) >= sub


@pytest.mark.parametrize("variant", ["nabeshima", "lageweg"])
def test_variant_cross_tier_parity_and_pairblock_compose(variant,
                                                         monkeypatch):
    """Each variant explores the identical tree on seq vs resident, with
    pair-blocking clamped to the smaller pair set (P = m-1 < Pb just means
    one block)."""
    ptm = taillard.reduced_instance(3, jobs=7, machines=5)

    def mk():
        return PFSPProblem(lb="lb2", ub=0, p_times=ptm, lb2_variant=variant)

    opt = sequential_search(mk()).best
    seq = sequential_search(mk(), initial_best=opt)
    monkeypatch.setenv("TTS_LB2_PAIRBLOCK", "8")  # > P=4: clamps to one block
    res = resident_search(mk(), m=4, M=64, K=8, initial_best=opt)
    assert (res.explored_tree, res.explored_sol) == (
        seq.explored_tree, seq.explored_sol
    )
    assert res.best == opt


def test_variant_checkpoint_identity(tmp_path):
    """A non-full variant prunes a different tree: its checkpoints must
    refuse to resume under another variant (and vice versa)."""
    from tpu_tree_search.engine import checkpoint as ckpt

    ptm = taillard.reduced_instance(5, jobs=7, machines=4)
    full = PFSPProblem(lb="lb2", ub=0, p_times=ptm)
    nab = PFSPProblem(lb="lb2", ub=0, p_times=ptm, lb2_variant="nabeshima")
    assert ckpt.problem_meta(full) != ckpt.problem_meta(nab)
    path = str(tmp_path / "v.ckpt")
    batch = {k: v for k, v in nab.root().items()}
    ckpt.save(path, nab, batch, best=10**9, tree=0, sol=0)
    with pytest.raises(ValueError, match="checkpoint is for"):
        ckpt.load(path, full)
    loaded = ckpt.load(path, nab)
    assert loaded.meta["lb2_variant"] == "nabeshima"
