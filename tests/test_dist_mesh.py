"""Distributed mesh-resident tier (per-host SPMD engines + host exchange):
the pod-scale composition must preserve the cross-tier determinism
invariant — exchanges move nodes and tighten incumbents, never create or
destroy work."""

from __future__ import annotations

import numpy as np
import pytest

from tpu_tree_search.engine import sequential_search
from tpu_tree_search.parallel.dist_mesh import dist_mesh_search
from tpu_tree_search.problems import NQueensProblem, PFSPProblem
from tpu_tree_search.problems.pfsp import taillard


def test_single_host_degenerates_to_mesh_parity():
    seq = sequential_search(NQueensProblem(N=10))
    res = dist_mesh_search(NQueensProblem(N=10), m=5, M=128, K=4, D=4)
    assert (res.explored_tree, res.explored_sol) == (
        seq.explored_tree, seq.explored_sol
    )
    assert res.complete


@pytest.mark.parametrize("H,D", [(2, 2), (2, 4), (4, 2)])
def test_two_hosts_match_sequential(H, D):
    seq = sequential_search(NQueensProblem(N=10))
    res = dist_mesh_search(
        NQueensProblem(N=10), m=5, M=128, K=4, D=D, num_hosts=H
    )
    assert (res.explored_tree, res.explored_sol) == (
        seq.explored_tree, seq.explored_sol
    )


def test_pfsp_fixed_incumbent_parity_and_ub0():
    ptm = taillard.reduced_instance(14, jobs=9, machines=5)
    opt = sequential_search(PFSPProblem(lb="lb1", ub=0, p_times=ptm)).best
    seq = sequential_search(
        PFSPProblem(lb="lb1", ub=0, p_times=ptm), initial_best=opt
    )
    res = dist_mesh_search(
        PFSPProblem(lb="lb1", ub=0, p_times=ptm), m=5, M=128, K=4,
        D=2, num_hosts=2, initial_best=opt,
    )
    assert (res.explored_tree, res.explored_sol, res.best) == (
        seq.explored_tree, seq.explored_sol, opt
    )
    # ub=0 (improving incumbent): the optimum must still be found; the
    # cross-host incumbent injection makes every host prune against the
    # global best.
    res0 = dist_mesh_search(
        PFSPProblem(lb="lb1", ub=0, p_times=ptm), m=5, M=128, K=4,
        D=2, num_hosts=2,
    )
    assert res0.best == opt


def test_skewed_partition_forces_donations():
    """Host 1 starts empty: it can only contribute via a real inter-host
    donation (download -> KV block -> upload), and totals must still hit
    the sequential goldens exactly."""

    def all_to_host0(warm, host_id, num_hosts):
        return {k: (v if host_id == 0 else v[:0]) for k, v in warm.items()}

    seq = sequential_search(NQueensProblem(N=11))
    res = dist_mesh_search(
        NQueensProblem(N=11), m=5, M=128, K=2, D=2, num_hosts=2,
        partition_fn=all_to_host0,
    )
    assert (res.explored_tree, res.explored_sol) == (
        seq.explored_tree, seq.explored_sol
    )
    assert res.comm is not None and res.comm["blocks_received"] > 0
    assert res.comm["nodes_sent"] == res.comm["nodes_received"]


def test_max_steps_budget_reports_incomplete():
    res = dist_mesh_search(
        NQueensProblem(N=12), m=5, M=64, K=1, rounds=1, D=2, num_hosts=2,
        max_steps=2,
    )
    assert not res.complete
    assert res.explored_tree > 0


def test_checkpoint_resume_lockstep_cuts(tmp_path):
    """Per-host lockstep cuts at exchange boundaries: interval 0 cuts every
    round; both files must carry the SAME "<uuid>:<round>" tag and format
    v3, resume must land exactly on the sequential goldens, and a tampered
    tag must be refused (the dist tier's coherence contract)."""
    import json

    from tpu_tree_search.engine import checkpoint as ckpt

    path = str(tmp_path / "dm.ckpt")
    prob = NQueensProblem(N=10)
    seq = sequential_search(prob)
    full = dist_mesh_search(
        prob, m=5, M=128, K=2, rounds=1, D=2, num_hosts=2,
        checkpoint_path=path, checkpoint_interval_s=0.0,
    )
    assert (full.explored_tree, full.explored_sol) == (
        seq.explored_tree, seq.explored_sol
    )
    tags = []
    for h in (0, 1):
        with np.load(path + f".h{h}") as data:
            header = json.loads(bytes(data["header"]).decode())
        # Multi-host files stamp the higher (multi-host) format version.
        assert header["version"] == ckpt.FORMAT_VERSION == 4
        assert header["hosts"] == 2
        tags.append(header["cut_tag"])
    assert tags[0] == tags[1] and ":" in str(tags[0])

    resumed = dist_mesh_search(
        NQueensProblem(N=10), m=5, M=128, K=2, rounds=1, D=2, num_hosts=2,
        resume_from=path,
    )
    assert (resumed.explored_tree, resumed.explored_sol) == (
        seq.explored_tree, seq.explored_sol
    )

    loaded = ckpt.load(path + ".h1", NQueensProblem(N=10), expect_hosts=2)
    ckpt.save(path + ".h1", prob, loaded.batch, loaded.best, loaded.tree,
              loaded.sol, hosts=2, cut_tag="deadbeef0000:999")
    with pytest.raises(ValueError, match="incoherent multi-host resume"):
        dist_mesh_search(
            NQueensProblem(N=10), m=5, M=128, K=2, rounds=1, D=2,
            num_hosts=2, resume_from=path,
        )


def test_budget_cutoff_cut_then_resume_to_goldens(tmp_path):
    """A max_steps cutoff with --checkpoint writes one final lockstep cut;
    resuming without the budget completes to the exact sequential
    goldens (counters continue across the cut)."""
    path = str(tmp_path / "dmcut.ckpt")
    prob = NQueensProblem(N=11)
    seq = sequential_search(prob)
    part = dist_mesh_search(
        prob, m=5, M=64, K=1, rounds=1, D=2, num_hosts=2,
        max_steps=2, checkpoint_path=path,
    )
    assert not part.complete
    import os

    assert os.path.exists(path + ".h0") and os.path.exists(path + ".h1")
    resumed = dist_mesh_search(
        NQueensProblem(N=11), m=5, M=64, K=2, rounds=1, D=2, num_hosts=2,
        resume_from=path,
    )
    assert (resumed.explored_tree, resumed.explored_sol) == (
        seq.explored_tree, seq.explored_sol
    )
    assert resumed.complete
