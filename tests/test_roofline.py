"""Memory-roofline audit (obs/roofline.py; `tts report --roofline`).

The byte-floor math, the peak-bandwidth resolution order (TTS_HBM_GBPS >
COSTMODEL `hbm` link > nominal backend table), the audit/table shapes, the
SearchResult.roofline field of a phase-profiled run, and the golden table
`tts report --roofline` prints from the committed trace + COSTMODEL
fixture pair (tests/data/roofline_*.json).
"""

from __future__ import annotations

import json
import os

import pytest

from tpu_tree_search import cli
from tpu_tree_search.obs import roofline as RL

DATA = os.path.join(os.path.dirname(__file__), "data")
TRACE = os.path.join(DATA, "roofline_trace.json")
COSTMODEL = os.path.join(DATA, "roofline_costmodel.json")


# -- byte floors ------------------------------------------------------------

def test_phase_byte_floors_off_path_golden():
    """Off path at (M=64, n=8, S=512, int32 pool): node = 8*4+4 = 36 B;
    every floor is the hand-derived figure from the module docstring."""
    f = RL.phase_byte_floors(M=64, n=8, S=512, itemsize=4)
    node, Mn = 36, 64 * 8
    assert f == {
        "pop": 64 * node,
        "eval": 64 * node + Mn * 4,
        "compact": Mn * 4 + 512 * 4,
        "push": 2 * 512 * node,
        "overflow": 0,
    }


def test_phase_byte_floors_megakernel_charges_eval():
    """Armed builds charge the whole fused cycle into `eval` (the phase
    the profiler books it under): streamed tiles in + the (M*n) int32
    emit + the pool-dtype write-back; compact/push floors are zero."""
    f = RL.phase_byte_floors(M=64, n=8, S=512, itemsize=1, megakernel=True)
    node, Mn = 8 * 1 + 4, 64 * 8
    assert f["pop"] == 64 * node
    assert f["eval"] == 64 * node + Mn * (8 + 1) * 4 + Mn * node
    assert f["compact"] == 0 and f["push"] == 0 and f["overflow"] == 0


# -- peak resolution order --------------------------------------------------

def test_peak_resolution_order(monkeypatch):
    entry = {"backend": "cpu", "links": {"hbm": {"per_sec": 25.6e9}}}
    # nominal fallback
    monkeypatch.delenv("TTS_HBM_GBPS", raising=False)
    bps, src = RL.peak_bytes_per_sec("tpu")
    assert (bps, src) == (RL.NOMINAL_GBPS["tpu"] * 1e9, "nominal:tpu")
    # a measured costmodel fit beats nominal
    bps, src = RL.peak_bytes_per_sec("cpu", entry)
    assert (bps, src) == (25.6e9, "costmodel:hbm")
    # the env override beats both
    monkeypatch.setenv("TTS_HBM_GBPS", "100")
    bps, src = RL.peak_bytes_per_sec("cpu", entry)
    assert (bps, src) == (100e9, "env:TTS_HBM_GBPS")
    monkeypatch.setenv("TTS_HBM_GBPS", "-1")
    with pytest.raises(ValueError):
        RL.hbm_gbps_override()


def test_peak_gpu_row_and_compound_key_fallback(monkeypatch):
    """The gpu nominal row (900 GB/s, the documented A100-PCIe-class
    placeholder) resolves for native-gpu profile keys; a forced non-native
    flavor's compound "platform+kind" key misses the table and falls
    through to the honest cpu row — an interpret run must never report
    itself against chip-class bandwidth. The override + measured-fit
    orders beat nominal on gpu exactly as on tpu."""
    monkeypatch.delenv("TTS_HBM_GBPS", raising=False)
    bps, src = RL.peak_bytes_per_sec("gpu")
    assert (bps, src) == (RL.NOMINAL_GBPS["gpu"] * 1e9, "nominal:gpu")
    assert RL.NOMINAL_GBPS["gpu"] == 900.0
    # profile_backend's compound key for a forced non-native flavor
    bps, src = RL.peak_bytes_per_sec("cpu+gpu")
    assert (bps, src) == (RL.NOMINAL_GBPS["cpu"] * 1e9, "nominal:cpu+gpu")
    entry = {"backend": "gpu", "links": {"hbm": {"per_sec": 3350e9}}}
    bps, src = RL.peak_bytes_per_sec("gpu", entry)
    assert (bps, src) == (3350e9, "costmodel:hbm")
    monkeypatch.setenv("TTS_HBM_GBPS", "1008")
    bps, src = RL.peak_bytes_per_sec("gpu", entry)
    assert (bps, src) == (1008e9, "env:TTS_HBM_GBPS")


def test_hbm_entry_picks_backend_match():
    prof = {
        "tpu|device-D1|x": {"backend": "tpu",
                            "links": {"hbm": {"per_sec": 819e9}}},
        "cpu|device-D1|x": {"backend": "cpu",
                            "links": {"dispatch": {"per_sec": 17.0}}},
        "cpu|device-D2|y": {"backend": "cpu",
                            "links": {"hbm": {"per_sec": 25.6e9}}},
    }
    e = RL.hbm_entry(prof, "cpu")
    assert e["links"]["hbm"]["per_sec"] == 25.6e9
    assert RL.hbm_entry({"k": {"backend": "cpu", "links": {}}}, "cpu") is None


# -- audit math -------------------------------------------------------------

def test_audit_pct_golden():
    """1 GB moved in 0.1 s against a 100 GB/s peak is 10 GB/s achieved =
    10% of peak; phases with no time or no floor get no percentage."""
    phase_ns = {"pop": int(0.1e9), "eval": 0, "overflow": int(1e6)}
    doc = RL.audit(phase_ns, cycles=1, M=2**25, n=8, S=0, itemsize=4,
                   peak_bps=100e9, peak_source="env:TTS_HBM_GBPS")
    rows = {r["phase"]: r for r in doc["phases"]}
    pop = rows["pop"]
    assert pop["bytes"] == 2**25 * (8 * 4 + 4)
    want_gbps = pop["bytes"] / 0.1 / 1e9
    assert pop["gbps"] == round(want_gbps, 2)
    assert pop["pct_of_peak"] == round(100.0 * want_gbps / 100.0, 1)
    assert "pct_of_peak" not in rows["eval"]      # no measured time
    assert "pct_of_peak" not in rows["overflow"]  # no byte floor
    assert doc["peak_gbps"] == 100.0 and doc["cycles"] == 1


def test_table_shape():
    doc = RL.audit({"pop": int(1e6)}, cycles=2, M=64, n=8, S=64,
                   itemsize=4, peak_bps=40e9, peak_source="nominal:cpu")
    lines = RL.table(doc)
    assert "peak 40.0 GB/s" in lines[0] and "2 cycles" in lines[0]
    assert any(line.lstrip().startswith("pop") for line in lines)
    assert len(lines) == 2 + len(RL.PHASES)


# -- the engine surface -----------------------------------------------------

def test_search_result_roofline_armed_by_phaseprof(monkeypatch):
    from tpu_tree_search.engine.resident import resident_search
    from tpu_tree_search.problems import NQueensProblem

    res = resident_search(NQueensProblem(N=8), m=4, M=64, K=8)
    assert res.roofline is None  # profiler off -> no payload
    monkeypatch.setenv("TTS_PHASEPROF", "1")
    res = resident_search(NQueensProblem(N=8), m=4, M=64, K=8)
    assert res.roofline is not None
    assert res.roofline["cycles"] > 0
    assert res.roofline["peak_source"].startswith(("nominal:", "env:",
                                                   "costmodel:"))
    rows = {r["phase"]: r for r in res.roofline["phases"]}
    assert set(rows) == set(RL.PHASES)
    assert rows["pop"]["bytes"] > 0


# -- the report surface (committed fixture pair) ----------------------------

def test_report_roofline_golden_table(capsys):
    """The committed phase-profiled trace + COSTMODEL pair prints the
    full table with the costmodel-resolved peak — the shape of every row
    is golden (floors are facts of the recorded meta, not of this host)."""
    assert cli.main(["report", TRACE, "--roofline",
                     "--costmodel", COSTMODEL]) == 0
    out = capsys.readouterr().out
    assert "roofline (peak 25.6 GB/s, costmodel:hbm; 36 cycles):" in out
    assert "phase       time_ms     floor_MB    GB/s     % of peak" in out
    for slot in RL.PHASES:
        assert f"\n    {slot}" in out
    # the overflow row reports time only — never a made-up percentage
    # (the 4-space indent is the roofline table; the 2-space "overflow
    # branch" row above it belongs to the phase-decomp table)
    over = [ln for ln in out.splitlines()
            if ln.startswith("    overflow")][0]
    assert over.rstrip().endswith("-")


def test_report_roofline_json_fields(capsys):
    assert cli.main(["report", TRACE, "--roofline",
                     "--costmodel", COSTMODEL, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    rl = doc["roofline"]
    assert rl["peak_source"] == "costmodel:hbm" and rl["cycles"] == 36
    assert {r["phase"] for r in rl["phases"]} == set(RL.PHASES)


def test_report_roofline_nominal_without_costmodel(capsys):
    """Without --costmodel the peak falls back to the nominal table for
    the recorded backend (the fixture ran on cpu)."""
    assert cli.main(["report", TRACE, "--roofline"]) == 0
    assert "nominal:cpu" in capsys.readouterr().out


def test_report_roofline_requires_profiled_trace(tmp_path, capsys):
    """--roofline on a trace without phase clocks is a hard exit 2 with a
    diagnostic; the same trace without the flag still reports fine."""
    evts = [{"name": "dispatch", "ph": "X", "ts": 0.0, "dur": 100.0,
             "pid": 0, "tid": 0, "args": {"cycles": 4}}]
    p = tmp_path / "plain.json"
    p.write_text(json.dumps({"traceEvents": evts}))
    assert cli.main(["report", str(p)]) == 0
    capsys.readouterr()
    assert cli.main(["report", str(p), "--roofline"]) == 2
    assert "phase-profiled" in capsys.readouterr().err


def test_report_bad_costmodel_exits_2(tmp_path, capsys):
    assert cli.main(["report", TRACE, "--roofline",
                     "--costmodel", str(tmp_path / "nope.json")]) == 2
    assert "cost model" in capsys.readouterr().err
