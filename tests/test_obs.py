"""Telemetry subsystem (`tpu_tree_search/obs/`, docs/OBSERVABILITY.md):
counter parity against engine counts, trace-file schema, the zero-cost
disabled path (byte-identical jaxprs), and guard interaction."""

from __future__ import annotations

import json

import pytest

from tpu_tree_search import cli
from tpu_tree_search.obs import capture, counters, events, export, report
from tpu_tree_search.problems import NQueensProblem, PFSPProblem


def _has_shard_map() -> bool:
    # jax_compat.shard_map covers both spellings; the mesh tier runs on
    # every supported jax build now, so this gate never skips.
    return True


# -- counter parity: obs totals must equal the engine's counts exactly ----


def test_seq_counter_parity():
    from tpu_tree_search.engine import sequential_search

    with capture() as cap:
        res = sequential_search(NQueensProblem(N=8))
    assert cap.explored_totals() == (res.explored_tree, res.explored_sol)
    assert (res.explored_tree, res.explored_sol) == (2056, 92)


def test_device_counter_parity_nqueens():
    from tpu_tree_search.engine.resident import resident_search

    with capture() as cap:
        res = resident_search(NQueensProblem(N=9), m=5, M=128)
    assert cap.explored_totals() == (res.explored_tree, res.explored_sol)
    # The device-phase totals come from the HARVESTED counter block, not
    # the engine's own sums (engine/resident._emit_device_explored), so
    # this equality exercises the on-device accumulation path itself.
    c = res.obs["device_counters"]
    assert c["pushed"] + res.phases[0].tree + res.phases[2].tree \
        == res.explored_tree
    assert c["leaves"] + res.phases[0].sol + res.phases[2].sol \
        == res.explored_sol
    # Structural invariants of the slot semantics.
    assert c["popped"] >= c["pushed"] // NQueensProblem(N=9).child_slots
    assert c["pool_hwm"] > 0
    assert c["surv_hwm"] > 0
    assert c["overflow"] >= 0


def test_device_counter_parity_pfsp_lb1():
    # Budgeted run (full Taillard searches take minutes on CPU): the
    # max_steps cutoff path emits the same explored samples, so parity
    # holds for partial counts too.
    from tpu_tree_search.engine.resident import resident_search

    with capture() as cap:
        res = resident_search(PFSPProblem(inst=1, lb="lb1", ub=1),
                              m=5, M=256, K=4, max_steps=3)
    assert res.explored_tree > 0
    assert cap.explored_totals() == (res.explored_tree, res.explored_sol)


@pytest.mark.skipif(not _has_shard_map(), reason="jax.shard_map unavailable")
def test_mesh_counter_parity():
    import jax

    from tpu_tree_search.parallel.resident_mesh import mesh_resident_search

    if len(jax.devices()) < 4:
        pytest.skip("needs >= 4 devices")
    with capture() as cap:
        res = mesh_resident_search(NQueensProblem(N=8), m=5, M=64, D=4)
    assert cap.explored_totals() == (res.explored_tree, res.explored_sol)
    assert (res.explored_tree, res.explored_sol) == (2056, 92)


def test_multi_counter_parity():
    import jax

    from tpu_tree_search.parallel.multidevice import multidevice_search

    D = min(4, len(jax.devices()))
    with capture() as cap:
        res = multidevice_search(NQueensProblem(N=8), m=5, M=64, D=D)
    assert cap.explored_totals() == (res.explored_tree, res.explored_sol)
    assert (res.explored_tree, res.explored_sol) == (2056, 92)


# -- zero-cost disabled path (routed through the contract registry) --------
# The byte-identity and cache-key claims are Contracts (obs/counters.py,
# engine/resident.py) checked over the whole knob matrix by `tts check`;
# these tests pin the same registry entries on the historical cell.


def test_disabled_mode_jaxpr_identical_and_counter_free():
    from tpu_tree_search.analysis import contracts, program_audit

    program_audit.load_contracts()
    art = program_audit.variant_artifact(
        "nqueens", labels=["off", "obs0", "obs-host", "obs1"]
    )
    # Disabled (and host-only) builds are byte-identical: counters are
    # compiled OUT, not branched — the 7-leaf carry of the original step;
    # the enabled build carries exactly one extra leaf (the counter block).
    assert contracts.run_one("obs-off-identity", art) == []
    assert contracts.run_one("obs-counter-block", art) == []


def test_program_cache_keys_on_obs():
    from tpu_tree_search.analysis import contracts, program_audit

    program_audit.load_contracts()
    art = program_audit.cache_key_artifact("nqueens")
    a, b = art.distinct["TTS_OBS"]
    assert b.obs and not a.obs
    assert contracts.run_one("program-cache-key-sound", art) == []


# -- trace file schema -----------------------------------------------------


def test_cli_trace_schema_and_report(tmp_path, capsys):
    trace = tmp_path / "t.json"
    metrics = tmp_path / "m.jsonl"
    assert cli.main([
        "nqueens", "--N", "8", "--tier", "device", "--m", "5", "--M", "64",
        "--trace", str(trace), "--metrics-file", str(metrics), "--json",
    ]) == 0
    out = capsys.readouterr().out
    assert "Trace written" in out
    rec = json.loads(out.strip().splitlines()[-1])
    assert rec["obs"]["device_counters"]["leaves"] == 92

    obj = json.loads(trace.read_text())
    evts = obj["traceEvents"]
    assert isinstance(evts, list) and evts
    # Metadata names every (pid, tid) track.
    assert any(e["ph"] == "M" and e["name"] == "process_name" for e in evts)
    assert any(e["ph"] == "M" and e["name"] == "thread_name" for e in evts)
    for e in evts:
        assert {"name", "ph", "pid", "tid"} <= set(e)
        if e["ph"] != "M":
            assert isinstance(e["ts"], (int, float))
        if e["ph"] == "X":
            assert e["dur"] >= 0
    names = {e["name"] for e in evts}
    assert {"dispatch", "explored", "device_counters"} <= names

    # Metrics JSONL: one flat object per counter sample.
    lines = [json.loads(ln) for ln in metrics.read_text().splitlines()]
    assert lines and all("ts_us" in r and "name" in r for r in lines)
    assert any(r["name"] == "device_counters" for r in lines)

    # tts report over the written trace prints all three summaries.
    assert cli.main(["report", str(trace)]) == 0
    rep = capsys.readouterr().out
    assert "steal efficiency" in rep
    assert "idle fraction per worker" in rep
    assert "cycle-rate timeline" in rep


def test_report_json_and_missing_file(tmp_path, capsys):
    assert cli.main(["report", str(tmp_path / "nope.json")]) == 2
    capsys.readouterr()
    trace = tmp_path / "t.json"
    with capture(trace_path=str(trace)):
        from tpu_tree_search.engine import sequential_search

        sequential_search(NQueensProblem(N=6))
    assert cli.main(["report", str(trace), "--json"]) == 0
    summary = json.loads(capsys.readouterr().out)
    assert {"steal", "idle", "cycle_rate", "events"} <= set(summary)


def test_report_truncated_trace_salvages_events(tmp_path, capsys):
    """Robustness contract: a killed writer's truncated trace is
    summarized as far as it parses — exit 0 with a warning."""
    trace = tmp_path / "t.json"
    with capture(trace_path=str(trace)):
        from tpu_tree_search.engine.resident import resident_search

        resident_search(NQueensProblem(N=9), m=8, M=128, K=4)
    full = trace.read_text()
    (tmp_path / "cut.json").write_text(full[: int(len(full) * 0.6)])
    assert cli.main(["report", str(tmp_path / "cut.json")]) == 0
    captured = capsys.readouterr()
    assert "salvaged" in captured.err
    assert "cycle-rate timeline" in captured.out


def test_report_empty_and_garbage_files_exit_zero(tmp_path, capsys):
    (tmp_path / "empty.json").write_text("")
    (tmp_path / "junk.json").write_text("not a trace at all")
    assert cli.main(["report", str(tmp_path / "empty.json"),
                     str(tmp_path / "junk.json")]) == 0
    captured = capsys.readouterr()
    assert "Warning" in captured.err
    assert "steal efficiency" in captured.out  # full report shape, zeros


def test_report_merges_multiple_metrics_files(tmp_path, capsys):
    """Multi-worker sessions write one metrics file per host; the report
    merges any mix of traces and metrics JSONL into one summary."""
    m1 = tmp_path / "h0.jsonl"
    m2 = tmp_path / "h1.jsonl"
    m1.write_text(json.dumps(
        {"ts_us": 10.0, "name": "explored", "host": 0, "worker": 0,
         "tree": 100, "sol": 2, "phase": 2}) + "\n")
    m2.write_text(json.dumps(
        {"ts_us": 12.0, "name": "explored", "host": 1, "worker": 0,
         "tree": 50, "sol": 1, "phase": 2}) + "\n"
        + "{torn line")  # mid-write kill tail: skipped, not fatal
    assert cli.main(["report", str(m1), str(m2), "--json"]) == 0
    captured = capsys.readouterr()
    summary = json.loads(captured.out)
    assert summary["events"] == 2
    assert summary["hosts"] == 2


def test_multi_trace_records_steals_and_idle(tmp_path):
    import jax

    from tpu_tree_search.parallel.multidevice import multidevice_search

    D = min(4, len(jax.devices()))
    with capture(mode="host") as cap:
        multidevice_search(NQueensProblem(N=8), m=5, M=64, D=D)
    s = cap.summary()
    # Worker tracks exist and the steal/idle sections are populated (the
    # termination scan guarantees at least one miss per worker).
    assert len(s["idle"]) == D
    assert s["steal"]["attempts"] >= 1


# -- guard interaction -----------------------------------------------------


def test_guard_green_with_obs(monkeypatch):
    """TTS_GUARD=1 + TTS_OBS=1 together: the counter block rides the
    existing dispatch result, so steady state must stay transfer- and
    recompile-free (the ISSUE 2 acceptance criterion)."""
    from tpu_tree_search.engine.resident import resident_search

    monkeypatch.setenv("TTS_GUARD", "1")
    with capture() as cap:
        res = resident_search(NQueensProblem(N=8), m=5, M=64)
    assert res.explored_sol == 92
    assert cap.explored_totals() == (res.explored_tree, res.explored_sol)


# -- events/export units ---------------------------------------------------


def test_recorder_thread_merge_and_disabled_noop(monkeypatch):
    import threading

    monkeypatch.delenv("TTS_OBS", raising=False)
    events.reset()
    events.emit("never")  # disabled: must not record
    assert events.drain() == []
    monkeypatch.setenv("TTS_OBS", "host")
    events.reset()

    def worker(wid):
        for _ in range(5):
            events.emit("tick", wid=wid)

    threads = [threading.Thread(target=worker, args=(w,)) for w in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    events.emit("tick", wid=99)
    evts = events.drain()
    assert len(evts) == 16
    assert [e["ts"] for e in evts] == sorted(e["ts"] for e in evts)
    assert {e["tid"] for e in evts} == {0, 1, 2, 99}


def test_counter_block_merge_semantics():
    import numpy as np

    a = np.zeros((counters.NSLOTS,), np.int64)
    b = np.zeros((counters.NSLOTS,), np.int64)
    a[counters.IDX["pushed"]] = 10
    a[counters.IDX["pool_hwm"]] = 100
    b[counters.IDX["pushed"]] = 5
    b[counters.IDX["pool_hwm"]] = 70
    total = counters.merge_host(counters.merge_host(None, a), b)
    assert total["pushed"] == 15  # additive
    assert total["pool_hwm"] == 100  # high-water mark
    stacked = counters.as_args(np.stack([a, b]))
    assert stacked["pushed"] == 15 and stacked["pool_hwm"] == 100


def test_export_roundtrip(tmp_path):
    evts = [
        {"name": "dispatch", "cat": "tts", "ph": "X", "ts": 10.0,
         "dur": 5.0, "pid": 0, "tid": 0, "args": {"cycles": 3, "tree": 7}},
        {"name": "explored", "cat": "metrics", "ph": "C", "ts": 16.0,
         "pid": 0, "tid": 0, "args": {"tree": 7, "sol": 1, "phase": 2}},
    ]
    path = tmp_path / "t.json"
    assert export.write_chrome_trace(evts, str(path)) == 2
    back = export.load_trace(str(path))
    assert back == evts  # metadata stripped, payload preserved
    s = report.summarize(back)
    assert s["events"] == 2
    assert s["cycle_rate"] and s["cycle_rate"][0]["dispatches"] == 1
