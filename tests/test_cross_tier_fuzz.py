"""Randomized cross-tier equivalence: every tier must reproduce the
sequential tier's exploredTree/exploredSol EXACTLY under a fixed incumbent,
on randomly generated instances — chunking, work stealing, diffusion
balancing, and mp-sharding may only permute visit order (SURVEY.md §4.2's
determinism invariant, fuzzed instead of fixed-instance)."""

from __future__ import annotations

import numpy as np
import pytest

from tpu_tree_search.engine.batched import batched_search
from tpu_tree_search.engine.device import device_search
from tpu_tree_search.engine.resident import resident_search
from tpu_tree_search.engine.sequential import sequential_search
from tpu_tree_search.parallel.dist import dist_search
from tpu_tree_search.parallel.dist_mesh import dist_mesh_search
from tpu_tree_search.parallel.multidevice import multidevice_search
from tpu_tree_search.parallel.resident_mesh import mesh_resident_search
from tpu_tree_search.problems import PFSPProblem


def _fuzz_all_tiers(seed: int, lb: str):
    rng = np.random.default_rng(seed)
    jobs = int(rng.integers(6, 9))
    machines = int(rng.integers(3, 6))
    ptm = np.ascontiguousarray(
        rng.integers(1, 100, size=(machines, jobs)).astype(np.int32)
    )

    def mk():
        return PFSPProblem(lb=lb, ub=0, p_times=ptm)

    # Fixed incumbent: solve once with ub=0, then pin every tier to the
    # optimum (the ub=1 regime of the reference's validity check).
    opt = sequential_search(mk()).best
    seq = sequential_search(mk(), initial_best=opt)
    golden = (seq.explored_tree, seq.explored_sol)

    results = {
        "device": device_search(mk(), m=4, M=64, initial_best=opt),
        "resident": resident_search(mk(), m=4, M=64, K=8, initial_best=opt),
        "mesh": mesh_resident_search(
            mk(), m=4, M=64, K=4, rounds=2, D=4, initial_best=opt
        ),
        "multi": multidevice_search(mk(), m=4, M=64, D=3, initial_best=opt),
        "dist": dist_search(
            mk(), m=4, M=64, D=2, num_hosts=2, initial_best=opt,
            steal_interval_s=0.005,
        ),
    }
    results["dist_mesh"] = dist_mesh_search(
        mk(), m=4, M=64, K=4, rounds=2, D=2, num_hosts=2, initial_best=opt
    )
    if lb == "lb2":
        results["mesh_mp"] = mesh_resident_search(
            mk(), m=4, M=64, K=4, rounds=2, D=4, mp=2, initial_best=opt
        )
        # The full composition: staged (when forced) + mp pair sharding
        # inside each host, host exchange between steps.
        results["dist_mesh_mp"] = dist_mesh_search(
            mk(), m=4, M=64, K=4, rounds=2, D=2, mp=2, num_hosts=2,
            initial_best=opt,
        )
    for tier, res in results.items():
        assert (res.explored_tree, res.explored_sol) == golden, (
            f"{tier} diverged on seed={seed} jobs={jobs} machines={machines} "
            f"lb={lb}: {(res.explored_tree, res.explored_sol)} != {golden}"
        )
        assert res.best == opt

@pytest.mark.parametrize(
    "seed,lb", [(11, "lb1"), (23, "lb1_d"), (47, "lb2")]
)
def test_all_tiers_match_sequential_on_random_instance(seed, lb):
    _fuzz_all_tiers(seed, lb)


@pytest.mark.parametrize("seed,lb", [(11, "lb1"), (47, "lb2")])
def test_batched_axis_matches_sequential(seed, lb):
    """The instance-batch axis (engine/batched.py, serve --batch-slots):
    3 identical tenants through a 2-slot batched program — slot refill
    included — must EACH land the sequential counts on a random
    instance; frozen-slot masking may never leak one tenant's updates
    into another. A dedicated test (not part of _fuzz_all_tiers) so the
    B=2 while-loop compiles once per bound family, not once per fuzz
    parametrization."""
    rng = np.random.default_rng(seed)
    jobs = int(rng.integers(6, 9))
    machines = int(rng.integers(3, 6))
    ptm = np.ascontiguousarray(
        rng.integers(1, 100, size=(machines, jobs)).astype(np.int32)
    )

    def mk():
        return PFSPProblem(lb=lb, ub=0, p_times=ptm)

    opt = sequential_search(mk()).best
    seq = sequential_search(mk(), initial_best=opt)
    golden = (seq.explored_tree, seq.explored_sol)
    for i, res in enumerate(
        batched_search(mk(), n_jobs=3, B=2, m=4, M=64, K=8,
                       initial_best=opt)
    ):
        assert (res.explored_tree, res.explored_sol) == golden, (
            f"batched job {i} diverged on seed={seed} jobs={jobs} "
            f"machines={machines} lb={lb}: "
            f"{(res.explored_tree, res.explored_sol)} != {golden}"
        )
        assert res.best == opt


@pytest.mark.parametrize("seed", [59, 83])
def test_all_tiers_match_sequential_staged_lb2(seed, monkeypatch):
    """The staged lb2 evaluator (forced via TTS_LB2_STAGED=1; the jnp self
    path stands in for the kernel on CPU) through every tier at once —
    the same determinism invariant, same shared body. Includes the
    dp x mp mesh: staging now composes with the sharded pair loop
    (`lb2_self_bounds_mp`), closing the silent-fallback hole."""
    monkeypatch.setenv("TTS_LB2_STAGED", "1")
    _fuzz_all_tiers(seed, "lb2")


@pytest.mark.parametrize("seed,pairblock,staged", [
    (101, "1", "0"),   # serial pair loop (degenerate old behavior)
    (101, "4", "0"),   # multi-block at these P (machines 3-5 -> P 3-10)
    (101, "4", "1"),   # blocked self bound through the staged evaluator
    (131, "auto", "1"),  # the default policy end to end
])
def test_all_tiers_match_sequential_pairblocked_lb2(seed, pairblock, staged,
                                                    monkeypatch):
    """Fuzz axis over the lb2 pair-block size: every tier — including the
    dp x mp mesh, where each shard blocks its own P/mp pair subset — must
    land the sequential counts under every block size, serial through
    auto, staged and unstaged."""
    monkeypatch.setenv("TTS_LB2_PAIRBLOCK", pairblock)
    monkeypatch.setenv("TTS_LB2_STAGED", staged)
    _fuzz_all_tiers(seed, "lb2")


@pytest.mark.parametrize("pipeline,kmode", [("0", None), ("2", "auto")])
def test_all_tiers_match_sequential_pipeline_axis(pipeline, kmode,
                                                  monkeypatch):
    """Dispatch-pipeline axis (engine/pipeline.py): speculative pipelined
    dispatch is EXACT — every tier must land the sequential counts with
    pipelining off (TTS_PIPELINE=0, the synchronous pre-pipeline loops)
    and with one speculative dispatch in flight plus the adaptive
    geometric-ladder K controller (TTS_PIPELINE=2 + TTS_K=auto, the
    defaults-and-then-some).  Bit-parity across this axis is the ISSUE 5
    acceptance criterion."""
    monkeypatch.setenv("TTS_PIPELINE", pipeline)
    if kmode is not None:
        monkeypatch.setenv("TTS_K", kmode)
    _fuzz_all_tiers(211, "lb1")


@pytest.mark.slow  # every tier recompiles under force; CI tests-megakernel runs it unfiltered
@pytest.mark.parametrize("seed,lb", [(173, "lb1"), (179, "lb2")])
def test_all_tiers_match_sequential_megakernel_axis(seed, lb, monkeypatch):
    """One-kernel cycle axis (ops/megakernel.py): with the fused Pallas
    cycle forced (interpret mode on CPU — same program, reference
    semantics), every tier that can arm it must land the sequential
    counts, and the tiers that refuse (mp pair sharding, lb1_d) must
    fall back bit-correct.  The megakernel changes WHERE the cycle runs,
    never what it counts."""
    monkeypatch.setenv("TTS_MEGAKERNEL", "force")
    _fuzz_all_tiers(seed, lb)


@pytest.mark.slow  # every tier recompiles under force+Mt; CI tests-megakernel runs it unfiltered
@pytest.mark.parametrize("seed,lb", [(173, "lb1"), (179, "lb2")])
def test_all_tiers_match_sequential_megakernel_tiled_axis(seed, lb,
                                                          monkeypatch):
    """Streamed-grid axis (ops/megakernel.py TTS_MEGAKERNEL_MT): a forced
    Mt=16 tiles every tier's M=64 pool 4-wide through the double-buffered
    grid — per-tile compaction plus the SMEM-carried cross-tile offset
    must land the sequential counts on every tier, armed or refused.
    Streaming changes how the cycle's bytes move, never what it counts."""
    monkeypatch.setenv("TTS_MEGAKERNEL", "force")
    monkeypatch.setenv("TTS_MEGAKERNEL_MT", "16")
    _fuzz_all_tiers(seed, lb)


@pytest.mark.slow  # every tier recompiles per TTS_NARROW token; CI tests-narrow runs it unfiltered
@pytest.mark.parametrize("mode", ["0", "auto"])
def test_all_tiers_match_sequential_narrow_axis(mode, monkeypatch):
    """Narrow-node-storage axis (problems/base.py TTS_NARROW): with host
    pools/staging at int8/int16 storage dtypes (auto) and with everything
    forced wide int32 (0), every tier must land the sequential counts —
    widening happens only inside evaluator arithmetic, so the dtype of
    the bytes at rest can never change what the search explores."""
    monkeypatch.setenv("TTS_NARROW", mode)
    _fuzz_all_tiers(193, "lb1")


@pytest.mark.parametrize("kb", ["jnp", "tpu"])
def test_all_tiers_match_sequential_kernel_backend_inert_axis(kb,
                                                              monkeypatch):
    """Kernel-backend knob axis (ops/backend.py TTS_KERNEL_BACKEND): the
    inert settings on this host — forced jnp, and forced tpu off-TPU
    (non-native, so routing stays on the jnp evaluators) — must land the
    sequential counts on every tier.  The `kernel-backend-inert` contract
    checks the jaxpr is byte-identical; this checks the search is."""
    monkeypatch.setenv("TTS_KERNEL_BACKEND", kb)
    _fuzz_all_tiers(227, "lb1")


@pytest.mark.slow  # forced gpu routes every tier through interpret-mode kernels; CI tests-gpu-lowering runs it unfiltered
@pytest.mark.parametrize("seed,lb", [(227, "lb1"), (229, "lb2")])
def test_all_tiers_match_sequential_kernel_backend_gpu_axis(seed, lb,
                                                            monkeypatch):
    """Forced-gpu axis: TTS_KERNEL_BACKEND=gpu (+ TTS_PALLAS=force to
    re-arm the demoted lb1 family) lowers every evaluator through the
    Triton-flavored tile bodies — interpret mode on this CPU host, same
    program — and every tier must still land the sequential counts.  The
    backend changes HOW the bounds are computed, never what the search
    explores."""
    monkeypatch.setenv("TTS_KERNEL_BACKEND", "gpu")
    monkeypatch.setenv("TTS_PALLAS", "force")
    _fuzz_all_tiers(seed, lb)


@pytest.mark.parametrize("mode", ["dense", "auto"])
def test_all_tiers_match_sequential_compact_axis(mode, monkeypatch):
    """Compaction-path axis (survivor-path overhaul): every tier — the
    fused prune+push runs shard-local inside mesh/dist_mesh via the shared
    loop body — must land the sequential counts under the dense shift path
    and under the auto policy.  The sort/search modes ride CI's dedicated
    per-mode tier-1 jobs (.github/workflows/ci.yml tests-compact)."""
    monkeypatch.setenv("TTS_COMPACT", mode)
    _fuzz_all_tiers(167, "lb1")


def _random_instance(seed: int, jobs: int, machines: int):
    rng = np.random.default_rng(seed)
    return np.ascontiguousarray(
        rng.integers(1, 100, size=(machines, jobs)).astype(np.int32)
    )


@pytest.mark.parametrize(
    "jobs,machines,lb,M",
    [
        (50, 10, "lb1", 256),   # ta031-class shapes through every size-
        (50, 10, "lb2", 64),    # dependent path (VERDICT r4 #6)
        (200, 10, "lb1", 128),  # int16 pool dtype (n > 127) engages
    ],
)
def test_large_instance_budgeted_resident_and_mesh(jobs, machines, lb, M,
                                                   tmp_path):
    """Large random instances end to end under a ``max_steps`` budget: the
    full search is intractable, but the size-dependent machinery — int8/
    int16 pool dtypes, `_auto_tile` shapes, the survivor-budget overflow
    fallback (a ub=0 infinite incumbent keeps nearly every child, far
    exceeding the survivor budget S = max(64n, Mn/4)) — must run, count,
    checkpoint, and resume at realistic widths. The reference cannot
    represent these nodes at all without a rebuild (MAX_JOBS=20,
    `Taillard.chpl:29-52`)."""
    from tpu_tree_search.engine.resident import _pool_int_dtype

    ptm = _random_instance(97 + jobs, jobs, machines)

    def mk():
        return PFSPProblem(lb=lb, ub=0, p_times=ptm)

    # The dtype claim the test name makes must actually hold.
    import jax.numpy as jnp

    assert _pool_int_dtype(jobs) == (jnp.int8 if jobs <= 127 else jnp.int16)

    path = str(tmp_path / "big.ckpt")
    r1 = resident_search(mk(), m=25, M=M, K=2, max_steps=1,
                         checkpoint_path=path)
    assert not r1.complete and r1.explored_tree > 0
    r2 = resident_search(mk(), m=25, M=M, K=2, max_steps=1,
                         resume_from=path)
    assert r2.explored_tree > r1.explored_tree  # resumed and progressed

    mres = mesh_resident_search(mk(), m=25, M=M, K=2, rounds=1, D=4,
                                max_steps=1)
    assert not mres.complete and mres.explored_tree > 0
    # Same frontier prefix, same fixed incumbent: the first budgeted step
    # explores nodes, never solutions (depth << jobs at step 1).
    assert mres.explored_sol == 0 and r1.explored_sol == 0


def test_large_instance_dist_runs_at_width_50():
    """The dist tier at 50-job width: a root-bound incumbent prunes every
    child immediately (lb1 of any deeper node >= the root bound), so the
    run terminates fast while still exercising 50-wide warm-up, per-host
    partitioning, the termination rounds, and the final reductions."""
    from tpu_tree_search.problems.pfsp import bounds as B

    ptm = _random_instance(147, 50, 10)
    prob = PFSPProblem(lb="lb1", ub=0, p_times=ptm)
    root_lb = B.lb1_bound(prob.lb1_data, np.arange(50, dtype=np.int32),
                          -1, 50)
    seq = sequential_search(
        PFSPProblem(lb="lb1", ub=0, p_times=ptm), initial_best=int(root_lb)
    )
    ds = dist_search(
        PFSPProblem(lb="lb1", ub=0, p_times=ptm), m=5, M=64, D=2,
        num_hosts=2, initial_best=int(root_lb), steal_interval_s=0.005,
    )
    assert (ds.explored_tree, ds.explored_sol) == (
        seq.explored_tree, seq.explored_sol
    )
    assert ds.best == root_lb  # no leaf can beat a lower bound


def test_survivor_budget_overflow_fallback_matches_goldens():
    """Force the resident engine's full-scatter fallback (`big` branch):
    N-Queens keeps every safe child, so a 512-parent chunk at shallow depth
    keeps ~512*(N-d) children >> S = max(64N, MN/2) — and the counts must
    still land exactly on the sequential goldens."""
    from tpu_tree_search.problems import NQueensProblem

    prob = NQueensProblem(N=11)
    seq = sequential_search(prob)
    res = resident_search(NQueensProblem(N=11), m=8, M=512, K=8)
    assert (res.explored_tree, res.explored_sol) == (
        seq.explored_tree, seq.explored_sol
    )
