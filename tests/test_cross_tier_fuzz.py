"""Randomized cross-tier equivalence: every tier must reproduce the
sequential tier's exploredTree/exploredSol EXACTLY under a fixed incumbent,
on randomly generated instances — chunking, work stealing, diffusion
balancing, and mp-sharding may only permute visit order (SURVEY.md §4.2's
determinism invariant, fuzzed instead of fixed-instance)."""

from __future__ import annotations

import numpy as np
import pytest

from tpu_tree_search.engine.device import device_search
from tpu_tree_search.engine.resident import resident_search
from tpu_tree_search.engine.sequential import sequential_search
from tpu_tree_search.parallel.dist import dist_search
from tpu_tree_search.parallel.multidevice import multidevice_search
from tpu_tree_search.parallel.resident_mesh import mesh_resident_search
from tpu_tree_search.problems import PFSPProblem


def _fuzz_all_tiers(seed: int, lb: str):
    rng = np.random.default_rng(seed)
    jobs = int(rng.integers(6, 9))
    machines = int(rng.integers(3, 6))
    ptm = np.ascontiguousarray(
        rng.integers(1, 100, size=(machines, jobs)).astype(np.int32)
    )

    def mk():
        return PFSPProblem(lb=lb, ub=0, p_times=ptm)

    # Fixed incumbent: solve once with ub=0, then pin every tier to the
    # optimum (the ub=1 regime of the reference's validity check).
    opt = sequential_search(mk()).best
    seq = sequential_search(mk(), initial_best=opt)
    golden = (seq.explored_tree, seq.explored_sol)

    results = {
        "device": device_search(mk(), m=4, M=64, initial_best=opt),
        "resident": resident_search(mk(), m=4, M=64, K=8, initial_best=opt),
        "mesh": mesh_resident_search(
            mk(), m=4, M=64, K=4, rounds=2, D=4, initial_best=opt
        ),
        "multi": multidevice_search(mk(), m=4, M=64, D=3, initial_best=opt),
        "dist": dist_search(
            mk(), m=4, M=64, D=2, num_hosts=2, initial_best=opt,
            steal_interval_s=0.005,
        ),
    }
    if lb == "lb2":
        results["mesh_mp"] = mesh_resident_search(
            mk(), m=4, M=64, K=4, rounds=2, D=4, mp=2, initial_best=opt
        )
    for tier, res in results.items():
        assert (res.explored_tree, res.explored_sol) == golden, (
            f"{tier} diverged on seed={seed} jobs={jobs} machines={machines} "
            f"lb={lb}: {(res.explored_tree, res.explored_sol)} != {golden}"
        )
        assert res.best == opt


@pytest.mark.parametrize(
    "seed,lb", [(11, "lb1"), (23, "lb1_d"), (47, "lb2")]
)
def test_all_tiers_match_sequential_on_random_instance(seed, lb):
    _fuzz_all_tiers(seed, lb)


@pytest.mark.parametrize("seed", [59, 83])
def test_all_tiers_match_sequential_staged_lb2(seed, monkeypatch):
    """The staged lb2 evaluator (forced via TTS_LB2_STAGED=1; the jnp self
    path stands in for the kernel on CPU) through every tier at once —
    the same determinism invariant, same shared body."""
    monkeypatch.setenv("TTS_LB2_STAGED", "1")
    _fuzz_all_tiers(seed, "lb2")
