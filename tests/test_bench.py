"""Unit tests for the bench harness's pure logic — the round's artifact
generator must not be the one untested component. Everything here runs in
milliseconds-to-seconds on CPU; the full end-to-end line is exercised by
running `python bench.py` (hardware sessions / CI smoke)."""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

import bench


def test_roofline_math_lb1():
    rl = bench.roofline(1_000_000.0, 20, 10, None, "lb1")
    # flops/parent = 4*n^2*m + 6*n*m = 16000 + 1200
    assert rl["flops_per_parent"] == 17_200
    assert rl["bound_evals_per_sec"] == 20_000_000.0
    assert rl["achieved_gflops"] == round(1e6 * 17_200 / 1e9, 2)
    assert 0 < rl["mfu_pct"] < 100


def test_roofline_math_lb2_includes_pairs():
    rl1 = bench.roofline(1000.0, 20, 10, 45, "lb2")
    rl2 = bench.roofline(1000.0, 20, 10, 90, "lb2")
    assert rl2["flops_per_parent"] > rl1["flops_per_parent"]


@pytest.mark.parametrize("lb", ["lb1", "lb2"])
def test_flop_model_matches_xla_cost_analysis(lb):
    """The hand FLOP model must track what the compiled evaluator actually
    executes (VERDICT r4 weak #5: the roofline was model-derived with no
    independent check — and the original lb2 model overstated work ~67x).
    XLA cost analysis is the arbiter; the model may differ by fusion /
    strength-reduction but not by an order of magnitude."""
    from tpu_tree_search.problems import PFSPProblem

    prob = PFSPProblem(lb=lb, inst=14, ub=1)
    measured = bench.flops_per_parent_xla(prob, lb)
    if measured is None:
        pytest.skip("backend exposes no XLA cost analysis (fallback path "
                    "covered by test_roofline_prefers_measured_flops)")
    assert measured > 0
    P = prob.lb2_data.pairs.shape[0] if lb == "lb2" else None
    model = bench.flops_per_parent_model(prob.jobs, prob.machines, P, lb)
    assert 1 / 3 <= measured / model <= 3, (measured, model)


def test_roofline_prefers_measured_flops():
    from tpu_tree_search.problems import PFSPProblem

    prob = PFSPProblem(lb="lb1", inst=14, ub=1)
    rl = bench.roofline(1_000_000.0, prob.jobs, prob.machines, None, "lb1",
                        problem=prob)
    if rl["flop_source"] == "xla_cost_analysis":
        assert rl["flops_per_parent"] > 0
    else:  # backend without cost analysis: falls back to the model
        assert rl["flops_per_parent"] == 17_200


def test_env_override_restores_and_pops(monkeypatch):
    monkeypatch.delenv("TTS_X_TEST", raising=False)
    with bench._env_override("TTS_X_TEST", "1"):
        assert os.environ["TTS_X_TEST"] == "1"
    assert "TTS_X_TEST" not in os.environ  # popped, not set to ""

    monkeypatch.setenv("TTS_X_TEST", "keep")
    with pytest.raises(RuntimeError):
        with bench._env_override("TTS_X_TEST", "1"):
            raise RuntimeError("boom")
    assert os.environ["TTS_X_TEST"] == "keep"  # restored on exception


def test_probe_pallas_honors_kill_switches(monkeypatch):
    monkeypatch.setenv("TTS_PALLAS", "0")
    ok1, err1, ok2, err2, ok3, err3 = bench.probe_pallas(timeout_s=5)
    assert not ok1 and "TTS_PALLAS=0" in err1

    monkeypatch.setenv("TTS_PALLAS", "1")
    monkeypatch.setenv("TTS_PALLAS_LB2", "0")
    # lb1 probe subprocess runs (and reports non-tpu backend on CPU).
    ok1, err1, ok2, err2, ok3, err3 = bench.probe_pallas(timeout_s=120)
    assert not ok1 and "not tpu" in err1


def test_record_last_good_roundtrip(tmp_path, monkeypatch):
    monkeypatch.setattr(bench, "LAST_GOOD_PATH", str(tmp_path / "lg.json"))
    rec = {"metric": "m", "value": 123.0, "vs_baseline": 1.0,
           "vs_ref_c_seq": 0.5, "pallas": True,
           "compact": {"picked": "sort"}}
    bench.record_last_good(rec)
    lg = bench.last_good()
    assert lg["value"] == 123.0 and lg["vs_ref_c_seq"] == 0.5
    assert lg["pallas"] is True and "commit" in lg and "date" in lg
    assert lg["compact"] == "sort"
    # A record without the A/B (express mode) stays writable.
    bench.record_last_good({"metric": "m", "value": 1.0, "vs_baseline": 1.0})
    assert bench.last_good()["compact"] is None


def test_contracts_fingerprint_provenance(tmp_path, monkeypatch):
    """ISSUE 8 satellite: every bench artifact records the committed
    compiled-program contract fingerprint, so a banked number is tied to
    the exact program structure it measured."""
    fp = bench.contracts_fingerprint()
    assert fp, "committed .tts-contracts.json missing or unreadable"
    monkeypatch.setenv("TTS_BENCH_PARTIAL", str(tmp_path / "p.json"))
    partial = bench.BenchPartial()
    assert partial.doc["contracts"] == fp
    with open(tmp_path / "p.json") as f:
        assert json.load(f)["contracts"] == fp
    # last-good rows carry it too
    monkeypatch.setattr(bench, "LAST_GOOD_PATH", str(tmp_path / "lg.json"))
    bench.record_last_good({"metric": "m", "value": 1.0, "vs_baseline": 1.0,
                            "contracts": fp})
    assert bench.last_good()["contracts"] == fp


def test_host_seq_parses_partial_rows(monkeypatch):
    """A timeout must keep the rows that already streamed (round-5
    contract: finished measurements survive)."""

    def fake_run(*a, **kw):
        raise subprocess.TimeoutExpired(
            cmd="x", timeout=1.0,
            output=(
                'HOST_SEQ_ROW {"tag": "pfsp_ta014_lb1", '
                '"nodes_per_sec": 1000.0, "parity": true}\n'
                "HOST_SEQ_ROW {torn"
            ).encode(),
        )

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    rows = bench.host_seq_extras(timeout_s=1.0)
    metrics = [r["metric"] for r in rows]
    assert "host_seq_pfsp_ta014_lb1_nodes_per_sec" in metrics
    assert rows[0]["vs_ref_c_seq"] == round(
        1000.0 / bench.REF_C_SEQ["pfsp_ta014_lb1"], 3
    )
    assert any("error" in r for r in rows)  # the timeout is still recorded


def test_host_seq_never_raises(monkeypatch):
    def fake_run(*a, **kw):
        raise OSError("no such executable")

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    rows = bench.host_seq_extras(timeout_s=1.0)
    assert rows and "error" in rows[0]


def test_host_seq_goldens_come_from_constants():
    """The child script's parity goldens must be substituted from the
    module constants (one source of truth), not hardcoded copies."""
    assert str(bench.GOLDEN_LB1["tree"]) in bench._HOST_SEQ
    assert str(bench.GOLDEN_LB2["tree"]) in bench._HOST_SEQ
    assert str(bench.NQ_SOL[14]) in bench._HOST_SEQ
    assert "@LB1_TREE@" not in bench._HOST_SEQ  # placeholders resolved


@pytest.mark.skipif(
    os.environ.get("TTS_BENCH_E2E", "0") != "1",
    reason="multi-minute end-to-end bench run; set TTS_BENCH_E2E=1 "
    "(hardware sessions / CI smoke run it)",
)
def test_express_mode_emits_minimal_tpu_gated_line():
    """End-to-end express run on CPU: one JSON line, parity true, no
    extras, backend recorded as cpu (so the watcher will NOT count it as
    a banked on-chip number), and BENCH_LAST_GOOD untouched."""
    lg_path = bench.LAST_GOOD_PATH
    before = open(lg_path).read() if os.path.exists(lg_path) else None
    env = {**os.environ, "TTS_BENCH_EXPRESS": "1",
           "PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu"}
    res = subprocess.run(
        [sys.executable, "bench.py"], capture_output=True, text=True,
        timeout=900, env=env,
        cwd=os.path.dirname(os.path.abspath(bench.__file__)),
    )
    line = res.stdout.strip().splitlines()[-1]
    rec = json.loads(line)
    assert rec["express"] is True
    assert rec["backend"] == "cpu"
    assert rec["parity"] is True and rec["value"] > 0
    assert rec["extra"] == []
    assert rec["pallas"] is False
    # The on_tpu banking guard: a CPU run must never touch the committed
    # BENCH_LAST_GOOD.json.
    after = open(lg_path).read() if os.path.exists(lg_path) else None
    assert after == before, "CPU express run clobbered BENCH_LAST_GOOD"


def test_pick_compact_selection_rules(monkeypatch):
    """pick_compact: fastest parity-passing wins; fast-but-wrong falls
    back to clean; per-mode failures are recorded, not fatal; all-fail
    returns (None, None)."""
    calls = []

    def run_fn():
        import os

        mode = os.environ["TTS_COMPACT"]
        calls.append(mode)
        if mode == "search":
            raise RuntimeError("compile boom")
        nps = {"scatter": 10.0, "sort": 99.0, "dense": 7.0}[mode]
        return (object(), nps, 0.0, 0.0)

    stats, best = bench.pick_compact(run_fn, lambda r: r[1] < 50)
    # sort is fastest but fails parity; scatter is the fastest clean pick.
    assert stats["picked"] == "scatter" and best[1] == 10.0
    assert stats["parity"] == {"scatter": True, "sort": False, "dense": True}
    assert "search" in stats["errors"]
    assert calls == ["scatter", "sort", "search", "dense"]

    def run_fail():
        raise RuntimeError("no backend")

    # All-fail: no best run, but the per-mode diagnostics survive.
    stats2, best2 = bench.pick_compact(run_fail, lambda r: True)
    assert best2 is None and stats2["picked"] is None
    assert set(stats2["errors"]) == set(bench.COMPACT_MODES)


def test_pick_compact_budget_skips_but_always_runs_first(monkeypatch):
    """The budget bounds total A/B wall time: the first mode always runs
    (the old single-mode floor), later modes are skipped and recorded."""
    import itertools

    t = itertools.count()
    monkeypatch.setattr(bench.time, "monotonic", lambda: next(t) * 100.0)

    def run_fn():
        return (object(), 5.0, 0.0, 0.0)

    stats, best = bench.pick_compact(run_fn, lambda r: True, budget_s=50.0)
    assert best is not None and stats["picked"] == "scatter"
    assert stats["skipped_budget"] == ["sort", "search", "dense"]


def test_pick_compact_records_decomposition_and_auto():
    """The stats blob shows WHY a mode won: per-mode device ms/cycle, the
    maintenance share against the evaluator-only calibration, and what the
    auto policy would have resolved for the config."""

    class _Diag:
        kernel_launches = 10

    class _Res:
        diagnostics = _Diag()

    def run_fn():
        import os

        nps = {"scatter": 10.0, "sort": 20.0, "search": 5.0, "dense": 8.0}
        return (_Res(), nps[os.environ["TTS_COMPACT"]], 1.0, 0.5)

    stats, best = bench.pick_compact(
        run_fn, lambda r: True, eval_ms=20.0, auto_mode="dense"
    )
    assert stats["picked"] == "sort" and stats["auto"] == "dense"
    d = stats["decomp"]["sort"]
    # 0.5s device phase / 10 cycles = 50 ms/cycle; 20 of it evaluator.
    assert d["cycle_ms"] == 50.0 and d["maint_ms"] == 30.0
