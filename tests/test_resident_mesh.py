"""Mesh-resident (SPMD) tier: parity on a virtual 8-device mesh.

Counting must be identical to the sequential anchor whenever the incumbent
is fixed — diffusion balancing only permutes visit order (SURVEY.md §4.2
cross-tier determinism); with an improving incumbent the tier must find the
same optimum (pmin all-reduce correctness).
"""

from __future__ import annotations

import numpy as np
import pytest

from tpu_tree_search.engine.sequential import sequential_search
from tpu_tree_search.parallel.resident_mesh import mesh_resident_search
from tpu_tree_search.problems import NQueensProblem, PFSPProblem
from tpu_tree_search.problems.pfsp import taillard


def test_nqueens_parity_and_balance():
    prob = NQueensProblem(N=10)
    seq = sequential_search(prob)
    res = mesh_resident_search(prob, m=8, M=128, K=8, rounds=2)
    assert (res.explored_tree, res.explored_sol) == (
        seq.explored_tree,
        seq.explored_sol,
    )
    # The diffusion balancer must spread the tree across shards: no single
    # shard may own (almost) everything on an 8-way mesh.
    per = np.asarray(res.per_worker_tree)
    if per.size > 1:
        assert per.max() < 0.8 * per.sum()


@pytest.mark.parametrize("lb", ["lb1", "lb2"])
def test_pfsp_fixed_incumbent_parity(lb):
    ptm = taillard.reduced_instance(14, jobs=10, machines=5)
    opt = sequential_search(PFSPProblem(lb=lb, ub=0, p_times=ptm)).best
    seq = sequential_search(PFSPProblem(lb=lb, ub=0, p_times=ptm), initial_best=opt)
    res = mesh_resident_search(
        PFSPProblem(lb=lb, ub=0, p_times=ptm), m=8, M=128, K=8, initial_best=opt
    )
    assert res.best == opt
    assert (res.explored_tree, res.explored_sol) == (
        seq.explored_tree,
        seq.explored_sol,
    )


def test_pfsp_improving_incumbent_pmin():
    ptm = taillard.reduced_instance(7, jobs=9, machines=6)
    seq = sequential_search(PFSPProblem(lb="lb1", ub=0, p_times=ptm))
    res = mesh_resident_search(PFSPProblem(lb="lb1", ub=0, p_times=ptm), m=8, M=128, K=8)
    assert res.best == seq.best


def test_saturation_fallback():
    # Genuine all-shard saturation: warm up to a frontier (1000+ nodes per
    # shard) that exceeds every shard's fan-out headroom (capacity 1500 -
    # M*n = ~800) while no shard starves, so diffusion moves nothing and
    # the step makes zero cycles — the host-offload fallback must engage
    # and counts must survive the round trips.
    prob = NQueensProblem(N=12)
    seq = sequential_search(prob)
    res = mesh_resident_search(
        prob, m=8, M=64, K=4, rounds=1, capacity=1500, warmup_target=8000
    )
    assert (res.explored_tree, res.explored_sol) == (
        seq.explored_tree,
        seq.explored_sol,
    )
    # The fallback's offloader transfers must be merged into the result's
    # diagnostics, not dropped (round-1 advisor finding c): every fallback
    # chunk is one H2D + one D2H on top of the pool re-uploads.
    d = res.diagnostics
    assert d.host_to_device > 1
    assert d.device_to_host >= d.host_to_device - 1


def test_single_device_mesh_degenerates():
    import jax

    prob = NQueensProblem(N=9)
    seq = sequential_search(prob)
    res = mesh_resident_search(prob, m=8, M=128, devices=jax.devices()[:1])
    assert (res.explored_tree, res.explored_sol) == (
        seq.explored_tree,
        seq.explored_sol,
    )


def test_mesh_resident_lb2_mp_axis_matches_sequential():
    """(dp, mp) two-axis mesh: the Johnson pair loop splits over mp (pmax
    combine) while the pool shards over dp. With ub=1 the explored counts
    must equal the flat-dp mesh AND the sequential tier exactly — the mp
    replicas stay in lockstep because pmax equalizes every prune decision."""
    ptm = taillard.reduced_instance(21, jobs=8, machines=6)
    mk = lambda: PFSPProblem(lb="lb2", ub=0, p_times=ptm)
    opt = sequential_search(mk()).best
    seq = sequential_search(mk(), initial_best=opt)
    r_mp = mesh_resident_search(
        mk(), m=4, M=64, K=4, rounds=2, D=4, mp=2, initial_best=opt
    )
    assert r_mp.best == opt
    assert (r_mp.explored_tree, r_mp.explored_sol) == (
        seq.explored_tree, seq.explored_sol
    )
    r_flat = mesh_resident_search(
        mk(), m=4, M=64, K=4, rounds=2, D=8, initial_best=opt
    )
    assert (r_flat.explored_tree, r_flat.explored_sol) == (
        seq.explored_tree, seq.explored_sol
    )


def test_mesh_resident_mp_rejects_non_lb2():
    with pytest.raises(ValueError, match="mp-axis"):
        mesh_resident_search(
            PFSPProblem(
                lb="lb1", ub=0,
                p_times=taillard.reduced_instance(14, jobs=6, machines=4)
            ),
            m=4, M=64, D=4, mp=2,
        )


def test_mesh_staged_lb2_parity(monkeypatch):
    """Staged lb2 inside shard_map (per-shard compaction + self bound, no
    collectives) must reproduce the single-pass mesh run node-for-node.
    TTS_LB2_STAGED=1 forces the staged structure on the CPU mesh (the jnp
    self path stands in for the kernel)."""
    ptm = taillard.reduced_instance(14, jobs=10, machines=5)
    opt = sequential_search(PFSPProblem(lb="lb2", ub=0, p_times=ptm)).best

    monkeypatch.setenv("TTS_LB2_STAGED", "0")
    base = mesh_resident_search(
        PFSPProblem(lb="lb2", ub=0, p_times=ptm), m=8, M=128, K=8,
        initial_best=opt,
    )
    monkeypatch.setenv("TTS_LB2_STAGED", "1")
    staged = mesh_resident_search(
        PFSPProblem(lb="lb2", ub=0, p_times=ptm), m=8, M=128, K=8,
        initial_best=opt,
    )
    assert (staged.explored_tree, staged.explored_sol, staged.best) == (
        base.explored_tree, base.explored_sol, base.best
    )


@pytest.mark.parametrize(
    "case", ["nqueens", "lb1", "lb2_staged", "lb2_unstaged"]
)
def test_mesh_pallas_inside_shard_map(case, monkeypatch):
    """Pallas kernels INSIDE the mesh tier's shard_map, off-chip via
    TTS_PALLAS_INTERPRET=1 — the regression for the round-5 hardware
    failure: jax >= 0.9's shard_map vma checker rejects pallas_call
    out_shapes at trace time (`test_mesh_staged_lb2_runs_on_tpu`,
    ValueError in pallas_call.py), which no CPU test could reach because
    use_pallas() is False off-TPU. The mesh step now passes
    check_vma=False; this drives the real routing + shard_map + kernel
    composition (kernel math interpreted) and pins exact parity."""
    monkeypatch.setenv("TTS_PALLAS_INTERPRET", "1")
    if case == "nqueens":
        prob = lambda: NQueensProblem(N=9)
        opt = None
    else:
        ptm = taillard.reduced_instance(14, jobs=10, machines=5)
        lb = "lb1" if case == "lb1" else "lb2"
        if case == "lb2_staged":
            monkeypatch.setenv("TTS_LB2_STAGED", "1")
        elif case == "lb2_unstaged":
            # The bench's staged-probe-failure degradation path: the
            # single-pass pfsp_lb2_bounds kernel inside shard_map.
            monkeypatch.setenv("TTS_LB2_STAGED", "0")
        prob = lambda: PFSPProblem(lb=lb, ub=0, p_times=ptm)
        opt = sequential_search(prob()).best
    seq = sequential_search(prob(), initial_best=opt)
    res = mesh_resident_search(prob(), m=8, M=128, K=8, initial_best=opt)
    assert (res.explored_tree, res.explored_sol) == (
        seq.explored_tree, seq.explored_sol
    )
    if opt is not None:
        assert res.best == opt
