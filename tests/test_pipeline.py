"""Async pipelined dispatch (TTS_PIPELINE) + adaptive K (TTS_K=auto).

The tentpole claims pinned here (engine/pipeline.py):

  * speculation is EXACT — a dispatch on a terminated pool is a zero-cycle
    no-op that changes no counter and loses no node (the invariant the
    whole design rests on);
  * bit-parity: resident/mesh results are identical at every pipeline
    depth and under the adaptive-K ladder;
  * steady state stays pure: pipelined dispatch triggers zero recompiles
    and zero implicit transfers under the guard, including across auto-K
    ladder resizes (each rung compiles once, on a sanctioned warm
    dispatch);
  * the offload tiers' double-buffered staging overlaps H2D with in-flight
    evaluation without changing counts;
  * obs span semantics stay truthful at depth > 1 (enqueue vs scalars-
    ready args, overlap-merged busy fractions, pipeline metadata).
"""

from __future__ import annotations

import numpy as np
import pytest

from tpu_tree_search.engine.pipeline import (
    AdaptiveK,
    DispatchQueue,
    resolve_k,
    resolve_pipeline_depth,
)
from tpu_tree_search.engine.resident import resident_search
from tpu_tree_search.engine.sequential import sequential_search
from tpu_tree_search.problems import NQueensProblem, PFSPProblem


# -- knob resolution -------------------------------------------------------


def test_pipeline_depth_resolution(monkeypatch):
    monkeypatch.delenv("TTS_PIPELINE", raising=False)
    assert resolve_pipeline_depth() == 2  # auto default
    assert resolve_pipeline_depth("0") == 1  # off = synchronous
    assert resolve_pipeline_depth("1") == 1
    assert resolve_pipeline_depth("2") == 2
    assert resolve_pipeline_depth("3") == 3
    monkeypatch.setenv("TTS_PIPELINE", "0")
    assert resolve_pipeline_depth() == 1
    monkeypatch.setenv("TTS_PIPELINE", "3")
    assert resolve_pipeline_depth() == 3
    with pytest.raises(ValueError):
        resolve_pipeline_depth("4")
    with pytest.raises(ValueError):
        resolve_pipeline_depth("fast")


def test_resolve_k_precedence(monkeypatch):
    monkeypatch.delenv("TTS_K", raising=False)
    assert resolve_k(4096, 4096) == (False, 4096)
    assert resolve_k("auto", 16) == (True, 16)
    with pytest.raises(ValueError):
        resolve_k("sometimes", 16)
    monkeypatch.setenv("TTS_K", "auto")
    # env auto wraps the param K as the ladder cap
    assert resolve_k(64, 4096) == (True, 64)
    monkeypatch.setenv("TTS_K", "128")
    assert resolve_k(4096, 4096) == (False, 128)
    monkeypatch.setenv("TTS_K", "bogus")
    with pytest.raises(ValueError):
        resolve_k(4096, 4096)


def test_adaptive_k_ladder_is_geometric():
    ctl = AdaptiveK(4096)
    assert ctl.ladder == (1, 4, 16, 64, 256, 1024, 4096)
    assert ctl.K == 1  # starts on the lowest rung
    small = AdaptiveK(8)
    assert small.ladder == (1, 2, 8)
    assert AdaptiveK(1).ladder == (1,)


def test_adaptive_k_observe_moves_along_ladder():
    ctl = AdaptiveK(4096, target=(0.100, 0.250))
    # fast dispatches climb one rung at a time, never past the cap
    changed = ctl.observe(0.001, cycles=1)
    assert changed and ctl.K == 4
    for _ in range(10):
        ctl.observe(0.0001 * ctl.K, cycles=ctl.K)  # 0.1 ms/cycle
    # per-cycle 0.1ms: climbs while the NEXT rung's full block is still
    # predicted inside the band (est*4 <= 0.25s) -> settles at K=1024
    # (102 ms/dispatch, inside the 100-250 ms target)
    assert ctl.K == 1024
    # a slow regime drops rungs until the full block fits the band again
    assert ctl.observe(ctl.K * 0.01, cycles=ctl.K)  # 10 ms/cycle
    assert ctl.K * 0.01 <= 0.25
    # inside the band: stable
    assert not ctl.observe(0.2, cycles=ctl.K)


def test_adaptive_k_ignores_empty_dispatches():
    ctl = AdaptiveK(64)
    assert not ctl.observe(0.0001, cycles=0)
    assert ctl.K == ctl.ladder[0]


def test_dispatch_queue_mechanics():
    q = DispatchQueue(2)
    assert not q.full and len(q) == 0
    q.push("a", 1.0)
    q.push("b", 2.0)
    assert q.full
    with pytest.raises(RuntimeError):
        q.push("c", 3.0)
    assert q.pop() == ("a", 1.0)
    assert list(q.drain()) == [("b", 2.0)]
    assert len(q) == 0


# -- program-level inertness (routed through the contract registry) ---------


def test_pipeline_and_guard_knobs_are_program_inert():
    """TTS_PIPELINE and TTS_GUARD are host-side knobs: flipping them must
    neither change the compiled step (byte-identity contracts declared in
    engine/pipeline.py / analysis/guard.py) nor fork the program cache
    (engine/resident.py's cache-key contract) — `tts check` verifies the
    same entries across the whole knob matrix."""
    from tpu_tree_search.analysis import contracts, program_audit

    program_audit.load_contracts()
    art = program_audit.variant_artifact(
        "nqueens", labels=["off", "pipe0", "pipe2", "guard1"]
    )
    assert contracts.run_one("pipeline-knob-inert", art) == []
    assert contracts.run_one("guard-knob-inert", art) == []
    keys = program_audit.cache_key_artifact("nqueens")
    assert contracts.run_one("program-cache-key-sound", keys) == []


# -- the no-op-dispatch invariant (what makes speculation exact) ------------


def test_speculative_dispatch_on_terminated_pool_is_noop():
    """A dispatch on a pool below the chunk threshold runs zero cycles:
    every counter increment is zero, size/best are unchanged, and the
    surviving rows are bit-identical — so a speculatively enqueued step
    after termination changes nothing."""
    import jax

    from tpu_tree_search.engine.device import warmup
    from tpu_tree_search.engine.resident import (
        _make_program,
        resolve_capacity,
    )
    from tpu_tree_search.pool import SoAPool
    from tpu_tree_search.problems.base import INF_BOUND, index_batch

    problem = NQueensProblem(N=8)
    m, M, K = 8, 64, 8
    capacity, M = resolve_capacity(problem, M, None)
    prog = _make_program(problem, m, M, K, capacity, jax.devices()[0])
    pool = SoAPool(problem.node_fields())
    pool.push_back(index_batch(problem.root(), 0))
    best = getattr(problem, "initial_ub", INF_BOUND)
    _, _, best = warmup(problem, pool, best, m)
    state = prog.init_state(pool.as_batch(), best)
    while True:
        out = prog.step(state)
        state = prog.carry(out)
        _, _, _, size, _, _ = prog.read_scalars(out)
        if size < m:
            break
    batch0, size0, best0 = prog.residual(state)
    batch0 = {k: v.copy() for k, v in batch0.items()}

    out = prog.step(state)  # the speculative no-op dispatch
    state2 = prog.carry(out)
    tree, sol, cycles, size1, best1, _ = prog.read_scalars(out)
    assert (tree, sol, cycles) == (0, 0, 0)
    assert (size1, best1) == (size0, best0)
    batch1, size2, _ = prog.residual(state2)
    assert size2 == size0
    for k in batch0:
        np.testing.assert_array_equal(batch0[k], batch1[k])


# -- bit-parity across depths / K schedules ---------------------------------


@pytest.mark.parametrize("depth", ["0", "2", "3"])
def test_resident_bit_parity_across_depths(depth, monkeypatch):
    monkeypatch.setenv("TTS_PIPELINE", depth)
    seq = sequential_search(NQueensProblem(N=9))
    res = resident_search(NQueensProblem(N=9), m=8, M=128, K=4)
    assert (res.explored_tree, res.explored_sol) == (
        seq.explored_tree, seq.explored_sol
    )
    assert res.pipeline_depth == resolve_pipeline_depth(depth)


def test_resident_bit_parity_auto_k(monkeypatch):
    monkeypatch.setenv("TTS_PIPELINE", "2")
    monkeypatch.setenv("TTS_K", "auto")
    seq = sequential_search(NQueensProblem(N=9))
    res = resident_search(NQueensProblem(N=9), m=8, M=128, K=8)
    assert (res.explored_tree, res.explored_sol) == (
        seq.explored_tree, seq.explored_sol
    )
    assert res.k_auto and res.k_resolved in (2, 8)


def test_mesh_bit_parity_pipelined(monkeypatch):
    import jax

    if len(jax.devices()) < 4:
        pytest.skip("needs the virtual multi-device CPU platform")
    from tpu_tree_search.parallel.resident_mesh import mesh_resident_search

    monkeypatch.setenv("TTS_PIPELINE", "2")
    monkeypatch.setenv("TTS_K", "auto")
    seq = sequential_search(NQueensProblem(N=9))
    res = mesh_resident_search(
        NQueensProblem(N=9), m=5, M=64, K=4, rounds=2, D=4
    )
    assert (res.explored_tree, res.explored_sol) == (
        seq.explored_tree, seq.explored_sol
    )


# -- steady-state purity under pipelining -----------------------------------


def test_pipelined_dispatch_zero_recompiles_under_guard(monkeypatch):
    """The acceptance guard test: TTS_PIPELINE=2 + TTS_K=auto completes a
    guarded run — every ladder rung compiles exactly once (its sanctioned
    warm dispatch) and every steady-state dispatch reuses the cached
    executable with zero implicit transfers; any violation raises."""
    monkeypatch.setenv("TTS_PIPELINE", "2")
    monkeypatch.setenv("TTS_K", "auto")
    res = resident_search(NQueensProblem(N=9), m=25, M=128, K=4, guard=True)
    assert res.complete
    assert res.diagnostics.kernel_launches > 2
    seq = sequential_search(NQueensProblem(N=9))
    assert res.explored_tree == seq.explored_tree


def test_pipelined_checkpoint_cut_is_coherent(tmp_path, monkeypatch):
    """A max_steps cutoff under speculation drains the in-flight
    dispatches before the snapshot, so saved counters match the saved
    frontier exactly: resume totals equal the uncut goldens."""
    monkeypatch.setenv("TTS_PIPELINE", "2")
    rng = np.random.default_rng(7)  # seed picked for a multi-dispatch tree
    ptm = np.ascontiguousarray(
        rng.integers(1, 100, size=(4, 8)).astype(np.int32)
    )

    def mk():
        return PFSPProblem(lb="lb1", ub=0, p_times=ptm)

    opt = sequential_search(mk()).best
    golden = sequential_search(mk(), initial_best=opt)
    path = str(tmp_path / "pipe.ckpt")
    r1 = resident_search(mk(), m=4, M=16, K=2, initial_best=opt,
                         max_steps=2, checkpoint_path=path)
    assert not r1.complete
    r2 = resident_search(mk(), m=4, M=16, K=2, initial_best=opt,
                         resume_from=path)
    assert (r2.explored_tree, r2.explored_sol) == (
        golden.explored_tree, golden.explored_sol
    )


# -- double-buffered offload staging ----------------------------------------


def test_offload_double_buffer_counts_and_parity():
    from tpu_tree_search.engine.device import device_search

    seq = sequential_search(NQueensProblem(N=9))
    res = device_search(NQueensProblem(N=9), m=5, M=64)
    assert (res.explored_tree, res.explored_sol) == (
        seq.explored_tree, seq.explored_sol
    )
    # The overlapped-H2D counter must register: nearly every steady-state
    # dispatch staged while the previous chunk was still in flight.
    assert res.diagnostics.double_buffered > 0
    assert res.diagnostics.double_buffered < res.diagnostics.host_to_device


def test_offloader_staging_reuses_two_buffers():
    import jax

    from tpu_tree_search.engine.device import DeviceOffloader

    problem = NQueensProblem(N=8)
    off = DeviceOffloader(problem, jax.devices()[0])
    chunk = problem.empty_batch(16)
    chunk["board"][:] = 1
    chunk["depth"][:] = 2
    chunk["board"][0] = 7  # distinguishable pad source
    a = off.stage(chunk, 10, 16)
    b = off.stage(chunk, 10, 16)
    c = off.stage(chunk, 10, 16)
    assert a is not b  # double buffer: alternate buffers...
    for k in a:
        assert a[k] is c[k]  # ...and the third stage reuses the first
    # padding clones row 0 into the tail (the pad_chunk convention)
    np.testing.assert_array_equal(
        a["board"][10:], np.broadcast_to(chunk["board"][0], (6, 8))
    )
    np.testing.assert_array_equal(a["board"][1:10], chunk["board"][1:10])


def test_multidevice_pipelined_workers_match_sequential():
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs the virtual multi-device CPU platform")
    from tpu_tree_search.parallel.multidevice import multidevice_search

    seq = sequential_search(NQueensProblem(N=9))
    res = multidevice_search(NQueensProblem(N=9), m=5, M=64, D=3)
    assert (res.explored_tree, res.explored_sol) == (
        seq.explored_tree, seq.explored_sol
    )


def test_multidevice_checkpoint_gate_flushes_inflight(tmp_path):
    """The PauseGate flush: a checkpoint taken mid-run must not lose a
    worker's in-flight chunk — the resumed totals equal the goldens."""
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs the virtual multi-device CPU platform")
    from tpu_tree_search.parallel.multidevice import multidevice_search

    seq = sequential_search(NQueensProblem(N=10))
    path = str(tmp_path / "multi.ckpt")
    # A tiny interval forces cuts during the run (every chunk boundary).
    res = multidevice_search(NQueensProblem(N=10), m=5, M=64, D=2,
                             checkpoint_path=path,
                             checkpoint_interval_s=0.01)
    assert (res.explored_tree, res.explored_sol) == (
        seq.explored_tree, seq.explored_sol
    )


# -- obs span semantics under pipelining ------------------------------------


def test_dispatch_spans_carry_pipeline_args(monkeypatch):
    from tpu_tree_search.obs import events as ev

    monkeypatch.setenv("TTS_OBS", "host")
    monkeypatch.setenv("TTS_PIPELINE", "2")
    ev.reset()
    resident_search(NQueensProblem(N=8), m=8, M=64, K=4)
    evts = ev.drain()
    dispatches = [e for e in evts if e.get("name") == "dispatch"]
    assert dispatches
    for e in dispatches:
        args = e["args"]
        assert args["pipeline_depth"] == 2
        # enqueue time is the span start; the blocked read is separate
        assert args["enqueue_us"] == e["ts"]
        assert args["read_wait_us"] <= e["dur"] + 1e-6
    pipe = [e for e in evts if e.get("name") == "pipeline"]
    assert pipe and pipe[0]["args"]["depth"] == 2


def test_report_busy_fraction_truthful_at_depth_2(monkeypatch):
    """Overlapping dispatch spans must union, not sum: busy fraction stays
    <= 1 even when depth-2 spans overlap on one track."""
    from tpu_tree_search.obs import events as ev
    from tpu_tree_search.obs.report import summarize

    monkeypatch.setenv("TTS_OBS", "host")
    monkeypatch.setenv("TTS_PIPELINE", "2")
    ev.reset()
    resident_search(NQueensProblem(N=9), m=8, M=128, K=2)
    summary = summarize(ev.drain())
    for w in summary["idle"].values():
        assert w["busy_fraction"] <= 1.0 + 1e-9


def test_report_busy_merges_synthetic_overlaps():
    from tpu_tree_search.obs.report import summarize

    evts = [
        {"name": "dispatch", "ph": "X", "ts": 0.0, "dur": 100.0,
         "pid": 0, "tid": 0, "args": {}},
        {"name": "dispatch", "ph": "X", "ts": 50.0, "dur": 100.0,
         "pid": 0, "tid": 0, "args": {}},
    ]
    s = summarize(evts)
    # union is [0, 150] over a 150us trace span -> busy fraction 1.0
    assert s["idle"]["h0/w0"]["busy_fraction"] == pytest.approx(1.0)


def test_trace_metadata_records_pipeline_depth(monkeypatch):
    from tpu_tree_search.obs import events as ev
    from tpu_tree_search.obs.export import chrome_trace_object

    monkeypatch.setenv("TTS_OBS", "host")
    monkeypatch.setenv("TTS_PIPELINE", "2")
    ev.reset()
    resident_search(NQueensProblem(N=8), m=8, M=64, K=4)
    obj = chrome_trace_object(ev.drain())
    assert obj["otherData"]["pipeline_depth"] == 2
    assert "k_initial" in obj["otherData"]


# -- the simulated-latency A/B (acceptance criterion) ------------------------


def test_simulated_latency_pipeline_hides_round_trip():
    """On the simulated-latency CPU harness the depth-2 host-loop wall
    time per dispatch drops by at least (a healthy fraction of) the
    injected scalar-read round trip — the acceptance bar for the
    pipeline, runnable with no TPU window."""
    import sys

    sys.path.insert(0, ".")
    import bench

    r = bench.simulated_latency_ab(m=25, M=512, K=8)
    assert r["depth1_ms_per_dispatch"] > r["depth2_ms_per_dispatch"]
    # full drop is round_trip (t_dev > round_trip by construction);
    # 0.5 slack absorbs CI scheduling noise
    assert r["drop_ms_per_dispatch"] >= 0.5 * r["round_trip_ms"], r
