"""Topology-aware hierarchical work stealing (parallel/topology.py,
TTS_STEAL=hier): link classification, cost-model-resolved per-level
periods/quanta, near-first/escalate-far matching with the far
amortization floor — and the cross-communicator guarantees: node counts
stay bit-identical to the flat default, and under injected asymmetric
link latency the hierarchy strictly reduces idle time (docs/PARALLELISM.md)."""

from __future__ import annotations

import pytest

from tpu_tree_search.engine import sequential_search
from tpu_tree_search.obs import costmodel as cm
from tpu_tree_search.parallel.topology import (
    FAR_EVERY_DEFAULT,
    FAR_QUANTUM_MULT,
    LINK_DCN,
    LINK_ICI,
    LINK_LOCAL,
    SimLinks,
    Topology,
    _parse_pods,
    resolve_policy,
    steal_mode,
)
from tpu_tree_search.problems import NQueensProblem


@pytest.fixture(autouse=True)
def _clean_steal_env(monkeypatch):
    for k in ("TTS_STEAL", "TTS_PODS", "TTS_SIM_LAT_ICI", "TTS_SIM_LAT_DCN",
              "TTS_COSTMODEL", "TTS_OBS"):
        monkeypatch.delenv(k, raising=False)


# -- knob + pod-map parsing ---------------------------------------------------


def test_steal_mode_default_flat_and_typo_safe(monkeypatch):
    assert steal_mode() == "flat"
    monkeypatch.setenv("TTS_STEAL", "hier")
    assert steal_mode() == "hier"
    monkeypatch.setenv("TTS_STEAL", "HIER ")
    assert steal_mode() == "hier"
    # an unrecognized value must never change semantics
    monkeypatch.setenv("TTS_STEAL", "hierarchical")
    assert steal_mode() == "flat"


def test_parse_pods_grammar():
    assert _parse_pods("2", 6) == [0, 0, 0, 1, 1, 1]
    assert _parse_pods("2", 4) == [0, 0, 1, 1]
    assert _parse_pods("3", 3) == [0, 1, 2]
    assert _parse_pods("0,0,1,1", 4) == [0, 0, 1, 1]
    # mismatched list length, non-positive K, garbage, empty -> None
    assert _parse_pods("0,1", 4) is None
    assert _parse_pods("0", 4) is None
    assert _parse_pods("two", 4) is None
    assert _parse_pods("", 4) is None


def test_topology_link_classes(monkeypatch):
    topo = Topology(4, [0, 0, 1, 1])
    assert topo.link_class(0, 0) == LINK_LOCAL
    assert topo.link_class(0, 1) == LINK_ICI
    assert topo.link_class(1, 2) == LINK_DCN
    assert topo.num_pods == 2
    # detect: TTS_PODS wins
    monkeypatch.setenv("TTS_PODS", "2")
    assert Topology.detect(4).pod_of == [0, 0, 1, 1]
    monkeypatch.delenv("TTS_PODS")
    # detect: slice indices assembled over the allgather
    det = Topology.detect(3, slice_index=1, allgather=lambda v: [0, 1, 1])
    assert det.pod_of == [0, 1, 1]
    # default: one pod, every inter-host link is ici
    one = Topology.detect(3)
    assert one.link_class(0, 2) == LINK_ICI


def test_sim_links_env_armed(monkeypatch):
    assert not SimLinks().armed
    monkeypatch.setenv("TTS_SIM_LAT_ICI", "0.001")
    sim = SimLinks()
    assert sim.armed
    assert sim.lat_s == {LINK_ICI: 0.001}
    sim.sleep(LINK_DCN)  # unarmed class: no-op, returns immediately
    monkeypatch.setenv("TTS_SIM_LAT_DCN", "not-a-float")
    assert SimLinks().lat_s == {LINK_ICI: 0.001}


# -- cost-model quantum / period resolution -----------------------------------


def _entry(ici_lat=None, dcn_lat=None, per_byte=0.0, eval_us=10.0):
    links = {"offload": {"per_unit_us": eval_us}}
    if ici_lat is not None:
        links["donate:ici"] = {"latency_us": ici_lat, "per_unit_us": per_byte}
    if dcn_lat is not None:
        links["donate:dcn"] = {"latency_us": dcn_lat, "per_unit_us": per_byte}
    return {"links": links}


def test_steal_quantum_amortization_formula():
    # Q >= lat / (frac*eval - bpn*per_byte): 100us latency, 10us/node
    # eval, frac 0.10 -> denom 1.0 -> Q = 100 nodes.
    e = _entry(ici_lat=100.0)
    assert cm.steal_quantum(e, "ici", m=5, bytes_per_node=0, cap=1000) == 100
    # clamped below by 2m (pop_front_bulk_half's donor threshold)...
    e = _entry(ici_lat=1.0)
    assert cm.steal_quantum(e, "ici", m=50, bytes_per_node=0, cap=1000) == 100
    # ...and above by cap
    e = _entry(ici_lat=1e6)
    assert cm.steal_quantum(e, "ici", m=5, bytes_per_node=0, cap=512) == 512
    # per-byte cost alone over budget -> maximally bulk (cap)
    e = _entry(ici_lat=100.0, per_byte=1.0, eval_us=5.0)
    assert cm.steal_quantum(e, "ici", m=5, bytes_per_node=64, cap=777) == 777
    # no fit for the link -> None (caller keeps the fixed fallback)
    assert cm.steal_quantum(_entry(), "dcn", m=5, bytes_per_node=0,
                            cap=100) is None


def test_steal_every_period_formula():
    # 2ms dcn latency over a 5ms round, frac 0.10 -> every 4th round
    e = _entry(dcn_lat=2000.0)
    assert cm.steal_every(e, 0.005) == 4
    # huge latency clamps at the cap
    e = _entry(dcn_lat=50000.0)
    assert cm.steal_every(e, 0.005, cap=32) == 32
    # floor of 2: a far round can never fire EVERY round
    e = _entry(dcn_lat=1.0)
    assert cm.steal_every(e, 0.005) == 2
    assert cm.steal_every(_entry(), 0.005) is None


def test_resolve_policy_flat_is_legacy(monkeypatch):
    topo = Topology(4, [0, 0, 1, 1])
    pol = resolve_policy(NQueensProblem(N=6), topo, m=5, cap=64,
                         interval_s=0.01)
    assert not pol.hier
    # flat: one cap on every link, describe() says so
    for link in (LINK_LOCAL, LINK_ICI, LINK_DCN):
        assert pol.cap_for(link) == 64
    d = pol.describe()
    assert d["mode"] == "flat"
    assert d["levels"]["any"]["quantum"] == 64


def test_resolve_policy_hier_fixed_fallbacks(monkeypatch):
    monkeypatch.setenv("TTS_STEAL", "hier")
    topo = Topology(4, [0, 0, 1, 1])
    pol = resolve_policy(NQueensProblem(N=6), topo, m=5, cap=64,
                         interval_s=0.01)
    assert pol.hier
    near, far = pol.levels[LINK_ICI], pol.levels[LINK_DCN]
    assert (near.level, near.every, near.quantum) == (1, 1, 64)
    assert (far.level, far.every) == (2, FAR_EVERY_DEFAULT)
    assert far.quantum == 64 * FAR_QUANTUM_MULT
    assert near.source == far.source == "fixed"
    assert far.period_s == pytest.approx(0.01 * FAR_EVERY_DEFAULT)
    d = pol.describe()
    assert set(d["levels"]) == {LINK_ICI, LINK_DCN}
    assert d["levels"][LINK_DCN]["quantum"] == far.quantum


def test_resolve_policy_reads_costmodel_profile(tmp_path, monkeypatch):
    # A synthetic measured profile: the resolved quanta/periods must come
    # from the fits (source = the profile key), not the fixed fallbacks.
    import json

    problem = NQueensProblem(N=6)
    key = cm.profile_key("cpu", "topo-x", cm.shape_class(problem))
    prof = {key: _entry(ici_lat=100.0, dcn_lat=2000.0)}
    path = tmp_path / "COSTMODEL.json"
    path.write_text(json.dumps(prof))
    monkeypatch.setenv("TTS_COSTMODEL", str(path))
    monkeypatch.setenv("TTS_STEAL", "hier")
    pol = resolve_policy(problem, Topology(4, [0, 0, 1, 1]), m=5, cap=64,
                         interval_s=0.005, backend="cpu", topo_str="topo-x")
    near, far = pol.levels[LINK_ICI], pol.levels[LINK_DCN]
    assert near.source == key and far.source == key
    assert near.quantum == 100            # amortization formula above
    assert far.every == 4                 # 2ms latency / (0.1 * 5ms)
    assert far.quantum >= near.quantum    # far is never smaller than near


# -- the two-level matching ---------------------------------------------------


def _hier_policy(pods, m=5, cap=64, monkeypatch=None):
    pol = resolve_policy(NQueensProblem(N=6), Topology(len(pods), pods),
                         m=m, cap=cap, interval_s=0.01, mode="hier")
    return pol


def test_match_prefers_near_link():
    pol = _hier_policy([0, 0, 1, 1])
    # host 1 is needy; donors 0 (same pod, ici) and 2 (cross-pod, dcn)
    # exist. The near donor must win even on a far round.
    assert pol.match([2, 0], [1], round_no=0) == [(0, 1)]


def test_match_far_only_on_far_rounds():
    pol = _hier_policy([0, 0, 1, 1])
    every = pol.levels[LINK_DCN].every
    # only a cross-pod donor exists for host 3's pod-mate-less need
    assert pol.match([0], [3], round_no=0) == [(0, 3)]
    for r in range(1, every):
        assert pol.match([0], [3], round_no=r) == []
    assert pol.match([0], [3], round_no=every) == [(0, 3)]


def test_match_far_amortization_floor():
    pol = _hier_policy([0, 0, 1, 1], m=5, cap=64)
    floor = max(4 * 5, pol.levels[LINK_DCN].quantum // 2)
    sizes = [0] * 4
    # a far donor below the floor must NOT ship scraps across the link
    sizes[0] = floor - 1
    assert pol.match([0], [3], round_no=0, sizes=sizes) == []
    sizes[0] = floor
    assert pol.match([0], [3], round_no=0, sizes=sizes) == [(0, 3)]
    # the floor never applies to near pairs
    assert pol.match([0], [1], round_no=0, sizes=[1, 0, 0, 0]) == [(0, 1)]


def test_match_is_deterministic_and_one_to_one():
    pol = _hier_policy([0, 0, 0, 1, 1, 1])
    donors, needy = [0, 3], [1, 2, 4]
    a = pol.match(donors, needy, round_no=0)
    b = pol.match(list(donors), list(needy), round_no=0)
    assert a == b  # same inputs on every host -> same pairs, no handshake
    assert len({d for d, _ in a}) == len(a)  # each donor used at most once
    assert {(0, 1), (3, 4)} == set(a)        # in-pod feeds, no crossing


# -- cross-communicator parity (the N-Queens invariance gate) -----------------


def test_dist_hier_counts_bit_identical(monkeypatch):
    from tpu_tree_search.parallel.dist import dist_search

    seq = sequential_search(NQueensProblem(N=9))
    monkeypatch.setenv("TTS_STEAL", "hier")
    monkeypatch.setenv("TTS_PODS", "2")
    res = dist_search(NQueensProblem(N=9), m=5, M=128, D=1, num_hosts=4)
    assert (res.explored_tree, res.explored_sol) == (
        seq.explored_tree, seq.explored_sol
    )
    # the resolved policy is surfaced on the result
    assert res.steal_policy["mode"] == "hier"
    assert res.steal_policy["pods"] == [0, 0, 1, 1]
    levels = res.steal_policy["levels"]
    assert {"every", "quantum", "period_s", "source"} <= set(levels[LINK_ICI])


def test_dist_mesh_hier_counts_bit_identical(monkeypatch):
    from tpu_tree_search.parallel.dist_mesh import dist_mesh_search

    seq = sequential_search(NQueensProblem(N=10))
    monkeypatch.setenv("TTS_STEAL", "hier")
    monkeypatch.setenv("TTS_PODS", "2")
    res = dist_mesh_search(NQueensProblem(N=10), m=5, M=128, K=4, D=2,
                           num_hosts=2)
    assert (res.explored_tree, res.explored_sol) == (
        seq.explored_tree, seq.explored_sol
    )
    assert res.steal_policy and res.steal_policy["mode"] == "hier"


# -- the flat-vs-hier A/B under injected asymmetric latency -------------------


def test_hier_beats_flat_under_injected_latency():
    """The bench harness' adversarial case (one rich host per pod, DCN two
    orders of magnitude slower than ICI): flat's topology-blind zip pairs
    across pods while same-pod donors sit unused; hier must land identical
    node counts with strictly less idle time. Wall time is asserted with a
    generous margin (the strict gate is bench.py steal_ab / hw_session
    stage 6c, which also banks STEAL_AB.json)."""
    from bench import steal_ab

    row = steal_ab()
    assert row["parity"], row
    assert row["hier_idle_frac"] < row["flat_idle_frac"], row
    assert row["hier_s"] < row["flat_s"] * 1.10, row


# -- observability: report table, flight recorder, live view ------------------


def test_report_per_link_steal_table():
    from tpu_tree_search.obs import report

    evts = [
        {"name": "steal", "ts": 0.0, "dur": 50.0, "pid": 0, "tid": 1,
         "args": {"link": "local", "nodes": 10, "bytes": 80}},
        {"name": "steal_miss", "ts": 10.0, "pid": 0, "tid": 1,
         "args": {"link": "local"}},
        {"name": "donate_send", "ts": 20.0, "dur": 200.0, "pid": 0, "tid": 9,
         "args": {"link": "ici", "nodes": 8, "bytes": 64}},
        {"name": "donate_recv", "ts": 30.0, "dur": 300.0, "pid": 1, "tid": 9,
         "args": {"link": "ici", "nodes": 8, "bytes": 64}},
        {"name": "donate_recv", "ts": 40.0, "dur": 900.0, "pid": 1, "tid": 9,
         "args": {"link": "dcn", "nodes": 64, "bytes": 512}},
        # a pre-hierarchy event without a link stamp: ignored, not crashed
        {"name": "steal", "ts": 50.0, "dur": 5.0, "pid": 0, "tid": 2,
         "args": {"nodes": 3}},
    ]
    links = report.summarize(evts)["steal_links"]
    assert links["local"] == {"attempts": 2, "hits": 1, "misses": 1,
                              "nodes": 10, "bytes": 80, "mean_cost_us": 50.0}
    assert links["ici"]["attempts"] == 1 and links["ici"]["hits"] == 1
    assert links["ici"]["mean_cost_us"] == 300.0
    assert links["dcn"]["nodes"] == 64
    text = report.render(report.summarize(evts))
    assert "ici" in text and "dcn" in text and "mean_cost" in text


def test_flightrec_steal_link_in_snapshot():
    from tpu_tree_search.obs.flightrec import FlightRecorder

    rec = FlightRecorder(always_on=True, snapshot_period_us=0.0)
    rec.heartbeat("dist", host=0, wid=0, seq=1, cycles=10)
    rec.note_steal(0, "dcn", 2)
    rec.heartbeat("dist", host=0, wid=0, seq=2, cycles=10)
    snap = rec.latest()
    assert snap["steal_link"] == "dcn"
    assert snap["steal_level"] == 2


def test_live_snapshot_prints_steal_level():
    from tpu_tree_search.obs.live import format_snapshot

    text = format_snapshot({"tier": "dist", "steal_link": "ici",
                            "steal_level": 1})
    assert "steal=ici" in text


def test_cli_json_and_banner_surface_policy(capsys, monkeypatch):
    from tpu_tree_search import cli

    monkeypatch.setenv("TTS_STEAL", "hier")
    monkeypatch.setenv("TTS_PODS", "2")
    assert cli.main(["nqueens", "--N", "8", "--tier", "dist", "--m", "5",
                     "--M", "64", "--hosts", "2", "--json"]) == 0
    import json

    out = capsys.readouterr().out
    rec = json.loads(out.strip().splitlines()[-1])
    assert rec["steal_policy"]["mode"] == "hier"
    assert rec["steal_policy"]["levels"][LINK_DCN]["every"] >= 2
    # the settings banner names the knob
    assert "TTS_STEAL" in out
