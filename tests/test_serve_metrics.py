"""Daemon observability (serve/metrics.py + the fleet-telemetry legs):
/metrics exposition correctness vs the live registry, /healthz fields,
SSE incumbent/done ordering and the done-vs-cancel race, the follow_job
reconnect dedupe, `tts top`, and per-job report lanes.

Everything runs on the virtual CPU platform with small shapes; daemons
are in-process on port 0.  Several tests use an HTTP-thread-only daemon
(scheduler never started) so queued-state behavior is deterministic —
same idiom as test_serve.test_queue_admission_control.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from tpu_tree_search.serve import VERSION
from tpu_tree_search.serve import metrics as serve_metrics
from tpu_tree_search.serve.server import ServeDaemon

_FINAL = ("done", "failed", "cancelled")

# Same shared small shape as test_serve: each daemon compiles it once.
NQ10 = {"problem": "nqueens", "N": 10, "M": 256}


def _post(base, path, payload):
    req = urllib.request.Request(
        base + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode())


def _get(base, path):
    try:
        with urllib.request.urlopen(base + path, timeout=30) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode())


def _scrape(base):
    """GET /metrics; assert the content type and that every sample line
    parses. Returns ``{name: {labels-tuple: value}}``."""
    with urllib.request.urlopen(base + "/metrics", timeout=30) as r:
        assert r.status == 200
        assert r.headers["Content-Type"] == serve_metrics.CONTENT_TYPE
        text = r.read().decode()
    return serve_metrics.parse_text(text)


def _wait_final(base, jid, timeout_s=180.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        code, rec = _get(base, f"/job/{jid}")
        assert code == 200, rec
        if rec["state"] in _FINAL:
            return rec
        time.sleep(0.1)
    raise AssertionError(f"job {jid} did not finish in {timeout_s}s")


@pytest.fixture
def daemon(tmp_path):
    d = ServeDaemon(port=0, state_dir=str(tmp_path / "state"))
    d.start()
    yield d
    d.scheduler.drain(timeout_s=30.0)
    d.close()


@pytest.fixture
def idle_daemon(tmp_path):
    """HTTP endpoints up, scheduler NOT started: submitted jobs stay
    queued forever, so queued-state HTTP behavior is deterministic."""
    d = ServeDaemon(port=0, state_dir=str(tmp_path / "state"))
    d._http_thread = threading.Thread(
        target=d._httpd.serve_forever, kwargs={"poll_interval": 0.2},
        daemon=True)
    d._http_thread.start()
    yield d
    d.close()


# -- exposition format: render + parse ---------------------------------------


def test_histogram_buckets_are_cumulative(tmp_path):
    d = ServeDaemon(port=0, state_dir=str(tmp_path / "s"))
    try:
        for v in (0.001, 0.3, 400.0):  # first bucket, mid bucket, overflow
            d.metrics.observe("tts_serve_run_seconds", v, {"cls": "c"})
        parsed = serve_metrics.parse_text(serve_metrics.render(d))
        b = parsed["tts_serve_run_seconds_bucket"]
        assert b[(("cls", "c"), ("le", "0.005"))] == 1
        assert b[(("cls", "c"), ("le", "0.5"))] == 2
        assert b[(("cls", "c"), ("le", "300.0"))] == 2  # 400 is past the top
        assert b[(("cls", "c"), ("le", "+Inf"))] == 3
        assert parsed["tts_serve_run_seconds_count"][(("cls", "c"),)] == 3
        assert parsed["tts_serve_run_seconds_sum"][
            (("cls", "c"),)] == pytest.approx(400.301)
    finally:
        # close() drains serve_forever, which never ran here.
        d._httpd.server_close()


def test_label_escaping_roundtrip_and_malformed_rejection(tmp_path):
    d = ServeDaemon(port=0, state_dir=str(tmp_path / "s"))
    try:
        weird = 'a"b\\c\nd'
        d.metrics.inc("tts_serve_admissions_total", {"outcome": weird})
        parsed = serve_metrics.parse_text(serve_metrics.render(d))
        assert parsed["tts_serve_admissions_total"][
            (("outcome", weird),)] == 1
        # build_info carries the version label; value is always 1.
        assert parsed["tts_serve_build_info"][(("version", VERSION),)] == 1
    finally:
        d._httpd.server_close()
    with pytest.raises(ValueError):
        serve_metrics.parse_text("this is not a metric line\n")


# -- /healthz ----------------------------------------------------------------


def test_healthz_fields_unstarted(idle_daemon):
    code, h = _get(idle_daemon.url, "/healthz")
    assert code == 200
    assert h["version"] == VERSION
    assert h["uptime_s"] >= 0.0
    assert h["workers_alive"] == 0
    assert h["ok"] is True  # scheduler never started: not degraded


def test_healthz_fields_started(daemon):
    code, h = _get(daemon.url, "/healthz")
    assert code == 200
    assert h["workers_alive"] >= 1 and h["workers"] >= h["workers_alive"]
    assert h["ok"] is True
    # wait_ready returns the same payload (submit uses it for error tags).
    from tpu_tree_search.serve.server import wait_ready

    got = wait_ready(daemon.url, timeout_s=10.0)
    assert got is not None and got["version"] == VERSION


# -- conflict counters (deterministic via the idle daemon) -------------------


def test_conflict_counters_by_endpoint(idle_daemon):
    base = idle_daemon.url
    code, sub = _post(base, "/submit", NQ10)
    assert code == 201
    # /result on a queued job: 409, counted under endpoint="result".
    assert _get(base, f"/job/{sub['id']}/result")[0] == 409
    # First cancel lands (queued -> cancelled); the second is a conflict.
    assert _post(base, f"/job/{sub['id']}/cancel", {})[0] == 200
    assert _post(base, f"/job/{sub['id']}/cancel", {})[0] == 409
    parsed = _scrape(base)
    conflicts = parsed["tts_serve_conflicts_total"]
    assert conflicts[(("endpoint", "result"),)] == 1
    assert conflicts[(("endpoint", "cancel"),)] == 1
    assert parsed["tts_serve_admissions_total"][
        (("outcome", "admitted"),)] == 1
    assert parsed["tts_serve_jobs"][(("state", "cancelled"),)] == 1


# -- SSE: done vs cancel, both orders ----------------------------------------


def test_stream_on_already_cancelled_job_sends_done(idle_daemon):
    # Order 1: the job reaches its terminal state BEFORE the stream
    # connects. The stream must immediately close with the final record.
    from tpu_tree_search.obs.live import iter_sse

    base = idle_daemon.url
    code, sub = _post(base, "/submit", NQ10)
    assert code == 201
    assert _post(base, f"/job/{sub['id']}/cancel", {})[0] == 200
    final = None
    with urllib.request.urlopen(
        base + f"/job/{sub['id']}/stream", timeout=30
    ) as resp:
        for event, payload in iter_sse(resp):
            if event == "done":
                final = payload
                break
    assert final is not None and final["state"] == "cancelled"


def test_stream_cancel_midstream_terminates_with_done(daemon):
    # Order 2: cancel arrives while the stream is live. The stream must
    # still terminate with a `done` frame carrying a terminal record —
    # never hang, never close without the terminal frame.
    from tpu_tree_search.obs.live import iter_sse

    base = daemon.url
    code, sub = _post(base, "/submit", {**NQ10, "N": 12, "K": 4})
    assert code == 201
    final, cancelled = None, False
    with urllib.request.urlopen(
        base + f"/job/{sub['id']}/stream", timeout=180
    ) as resp:
        for event, payload in iter_sse(resp):
            if event == "done":
                final = payload
                break
            if not cancelled:
                cancelled = True
                _post(base, f"/job/{sub['id']}/cancel", {})
    # The race is real: the job may finish before the cancel flag is
    # seen. Either way the stream terminated with a terminal record.
    assert final is not None and final["state"] in _FINAL
    code, rec = _get(base, f"/job/{sub['id']}")
    assert rec["state"] == final["state"]


# -- /metrics under load vs the registry (the acceptance check) --------------


def test_metrics_scrape_under_load_matches_registry(daemon):
    base = daemon.url
    subs = []
    for n in (9, 10, 10):  # three concurrent jobs across two classes
        code, sub = _post(base, "/submit",
                          {"problem": "nqueens", "N": n, "M": 256})
        assert code == 201
        subs.append(sub)
    # Scrape while jobs admit/run/complete: every scrape must parse.
    scrapes = 0
    deadline = time.monotonic() + 180.0
    while time.monotonic() < deadline:
        parsed = _scrape(base)
        scrapes += 1
        done = parsed["tts_serve_jobs"].get((("state", "done"),), 0)
        if done == len(subs):
            break
        time.sleep(0.2)
    assert scrapes >= 2, "expected scrapes during the run, not just after"
    for sub in subs:
        assert _wait_final(base, sub["id"])["state"] == "done"

    parsed = _scrape(base)
    jobs = daemon.registry.all()
    # Gauges agree with the registry read the same way an operator would
    # cross-check them.
    assert parsed["tts_serve_jobs"][(("state", "done"),)] == len(jobs) == 3
    assert parsed["tts_serve_admissions_total"][
        (("outcome", "admitted"),)] == 3
    classes = {j.class_key for j in jobs}
    assert len(classes) == 2
    admitted = parsed["tts_serve_class_jobs_admitted"]
    assert {(("cls", c),) for c in classes} <= set(admitted)
    # Flow counters: every job ran >= 1 slice; first-slice queue waits
    # were observed once per job.
    slices = parsed["tts_serve_slices_total"]
    assert sum(slices.values()) >= 3
    assert {lab[0][1] for lab in slices} == classes
    qw = parsed["tts_serve_queue_wait_seconds_count"]
    assert sum(qw.values()) == 3
    rs = parsed["tts_serve_run_seconds_count"]
    assert sum(rs.values()) == sum(slices.values())
    assert parsed["tts_serve_uptime_seconds"][()] > 0
    assert parsed["tts_serve_queue_depth"][()] == 0
    _, h = _get(base, "/healthz")
    assert parsed["tts_serve_workers_alive"][()] == h["workers_alive"]
    # Per-class compile attribution surfaced as counters: both classes
    # compiled cold, the warm same-class admission compiled nothing.
    prog = parsed["tts_serve_new_programs_total"]
    assert sum(prog.values()) >= 2
    # Device-resident pool bytes: every class ran (a resident program is
    # cached), so its footprint gauge is positive and matches the pool's
    # own accounting — the HBM number `tts top` renders per class.
    from tpu_tree_search.serve.pool import resident_pool_bytes

    pool_bytes = parsed["tts_serve_pool_bytes"]
    assert {(("cls", c),) for c in classes} <= set(pool_bytes)
    for entry in daemon.pool.stats():
        assert pool_bytes[(("cls", entry["class"]),)] == entry["pool_bytes"]
        assert entry["pool_bytes"] > 0
    with daemon.pool._lock:
        entries = list(daemon.pool._classes.values())
    assert all(resident_pool_bytes(e.problem) == e.stats()["pool_bytes"]
               for e in entries)


# -- follow_job reconnect dedupe (the `tts watch --job` reprint bug) ---------


def test_follow_job_dedupes_reconnect_replays():
    # A fake daemon whose stream drops once mid-job: the first connection
    # replays snapshot A + incumbent n=1 then dies without `done`; the
    # reconnect replays BOTH again (exactly what the real server does:
    # per-connection send counters) plus the new n=2 and the terminal
    # frame. The client must emit each snapshot/incumbent exactly once.
    from tpu_tree_search.serve.client import follow_job

    snap = {"ts_us": 111, "seq": 1, "step": 1, "tier": "resident"}
    inc1 = {"t_s": 0.0, "step": 1, "best": 50, "nodes": 4, "n": 1,
            "job": "j1"}
    inc2 = {"t_s": 0.5, "step": 2, "best": 40, "nodes": 9, "n": 2,
            "job": "j1"}
    final = {"id": "j1", "state": "done", "result": {"best": 40}}
    streams = []

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            if self.path == "/job/j1/stream":
                streams.append(1)
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.end_headers()
                frames = [(None, snap), ("incumbent", inc1)]
                if len(streams) > 1:  # the reconnect replays + continues
                    frames += [("incumbent", inc2), ("done", final)]
                for event, payload in frames:
                    if event:
                        self.wfile.write(f"event: {event}\n".encode())
                    self.wfile.write(
                        f"data: {json.dumps(payload)}\n\n".encode())
                # Fall off the end: connection 1 drops without `done`.
            elif self.path == "/job/j1":
                body = json.dumps({"id": "j1", "state": "running"}).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self.send_response(404)
                self.end_headers()

        def log_message(self, *a):
            pass

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    t = threading.Thread(target=httpd.serve_forever,
                         kwargs={"poll_interval": 0.1}, daemon=True)
    t.start()
    try:
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        snaps, incs = [], []
        rec = follow_job(base, "j1", emit=snaps.append,
                         on_incumbent=incs.append, timeout_s=30.0)
        assert rec == final
        assert len(streams) >= 2, "test needs an actual reconnect"
        assert snaps == [snap]  # replayed snapshot emitted once
        assert [p["n"] for p in incs] == [1, 2]  # n=1 replay suppressed
    finally:
        httpd.shutdown()
        httpd.server_close()


# -- `tts top` ---------------------------------------------------------------


def test_top_once_smoke(idle_daemon, capsys):
    from tpu_tree_search import cli

    base = idle_daemon.url
    assert _post(base, "/submit", NQ10)[0] == 201
    port = str(idle_daemon.port)
    assert cli.main(["top", "--port", port, "--once"]) == 0
    out = capsys.readouterr().out
    assert f"tts serve v{VERSION}" in out
    assert "queued=1" in out
    assert cli.main(["top", "--port", port, "--once", "--json"]) == 0
    payload = json.loads(capsys.readouterr().out.strip())
    assert payload["health"]["version"] == VERSION
    assert payload["jobs"][0]["state"] == "queued"
    assert isinstance(payload["classes"], list)


def test_top_unreachable_daemon(capsys):
    from tpu_tree_search import cli

    assert cli.main(["top", "--port", "1", "--once"]) == 2
    assert "no serve daemon" in capsys.readouterr().err


# -- per-job report lanes + quality table ------------------------------------


def test_report_job_lanes_and_quality_sections():
    # Synthetic merged-daemon trace: two tenants, one with a quality
    # trajectory against the committed ta014 optimum.
    from tpu_tree_search.obs import report

    def ev(name, ts, job, args=None, **extra):
        return {"name": name, "cat": "tts", "ph": "i", "ts": ts,
                "pid": 0, "tid": 0, "args": args or {}, "job": job,
                **extra}

    evts = [
        ev("quality_ref", 0.0, "job-1",
           {"instance": "ta014", "optimum": 1377}),
        ev("dispatch", 0.0, "job-1", {"cycles": 100, "tree": 10,
                                      "best": 1500}, ph="X", dur=1e6),
        ev("incumbent", 1e6, "job-1", {"best": 1500}),
        ev("incumbent", 2e6, "job-1", {"best": 1377}),
        ev("dispatch", 5e5, "job-2", {"cycles": 50, "tree": 5},
           ph="X", dur=1e6),
    ]
    summary = report.summarize(evts)
    lanes = summary["jobs"]
    assert set(lanes) == {"job-1", "job-2"}
    assert lanes["job-1"]["dispatches"] == 1
    assert lanes["job-1"]["best"] == 1500
    q = summary["quality"]
    assert q["instance"] == "ta014" and q["optimum"] == 1377
    pts = q["jobs"]["job-1"]["points"]
    assert [p["best"] for p in pts] == [1500, 1377]
    assert pts[0]["gap"] == pytest.approx(123 / 1377, abs=1e-6)
    assert q["jobs"]["job-1"]["final_gap"] == 0.0
    # Span is 2s; gap is capped (1.0) until t=1s, then 123/1377, then 0
    # at t=2s -> integral (1.0 + 123/1377) / 2.
    assert q["jobs"]["job-1"]["primal_integral"] == pytest.approx(
        (1.0 + 123 / 1377) / 2, abs=1e-4)
    text = report.render(summary)
    assert "per-job lanes:" in text
    assert "quality vs time (instance ta014, optimum 1377):" in text
    assert "final gap 0.00%" in text and "primal integral" in text


def test_report_quality_from_daemon_job(daemon, monkeypatch):
    # End-to-end lane attribution: run one job through the daemon with
    # host-side event recording armed, watch its stream, then summarize
    # the drained events. Covers both fleet-telemetry claims at once:
    # the live stream interleaves incumbent frames before `done` (the
    # quality anchor guarantees at least one), and the scheduler's
    # job_context stamps every engine event so the report grows a lane.
    from tpu_tree_search.obs import events as obs_events
    from tpu_tree_search.obs import report
    from tpu_tree_search.obs.live import iter_sse

    monkeypatch.setenv("TTS_OBS", "host")
    obs_events.reset()
    base = daemon.url
    code, sub = _post(base, "/submit", NQ10)
    assert code == 201
    order, incumbents, final = [], [], None
    with urllib.request.urlopen(
        base + f"/job/{sub['id']}/stream", timeout=180
    ) as resp:
        for event, payload in iter_sse(resp):
            order.append(event or "snapshot")
            if event == "done":
                final = payload
                break
            if event == "incumbent":
                incumbents.append(payload)
    assert final is not None and final["state"] == "done"
    assert incumbents, "no incumbent frame before job completion"
    assert order.index("incumbent") < order.index("done")
    p = incumbents[0]
    assert p["job"] == sub["id"] and p["n"] == 1
    assert {"t_s", "step", "best", "nodes"} <= set(p)
    # Indices are monotone 1-based: the client dedupe key.
    assert [q["n"] for q in incumbents] == list(
        range(1, len(incumbents) + 1))

    evts = obs_events.drain()
    assert evts, "TTS_OBS=host recorded nothing"
    stamped = [e for e in evts if e.get("job") == sub["id"]]
    assert stamped, "no events carried the job id"
    summary = report.summarize(evts)
    assert sub["id"] in summary["jobs"]
    assert summary["jobs"][sub["id"]]["dispatches"] >= 1
