"""Multi-device runtime tests on the virtual 8-device CPU platform
(SURVEY.md §4 implication (b): cross-tier equivalence; (d): WS/termination
under a fake multi-device runtime)."""

import numpy as np
import pytest

from tpu_tree_search.engine import sequential_search
from tpu_tree_search.parallel.multidevice import multidevice_search
from tpu_tree_search.problems import NQueensProblem, PFSPProblem
from tpu_tree_search.problems.pfsp import taillard as T
from tpu_tree_search.utils import TaskStates


def test_task_states_sticky_allidle():
    s = TaskStates(3)
    assert not s.all_idle()
    s.set_idle(0)
    s.set_idle(1)
    assert not s.all_idle()
    s.set_idle(2)
    assert s.all_idle()
    s.set_busy(0)  # sticky: flag already latched (`util.chpl:16-21`)
    assert s.all_idle()


@pytest.mark.parametrize("D", [2, 4])
def test_nqueens_multi_matches_sequential(D):
    seq = sequential_search(NQueensProblem(N=9))
    md = multidevice_search(NQueensProblem(N=9), m=10, M=256, D=D)
    assert md.explored_sol == seq.explored_sol
    assert md.explored_tree == seq.explored_tree
    assert len(md.per_worker_tree) == D


@pytest.mark.parametrize("lb", ["lb1", "lb2"])
def test_pfsp_multi_finds_optimum_ub0(lb):
    ptm = T.reduced_instance(14, jobs=7, machines=5)
    seq = sequential_search(PFSPProblem(lb=lb, ub=0, p_times=ptm))
    md = multidevice_search(
        PFSPProblem(lb=lb, ub=0, p_times=ptm), m=5, M=128, D=4
    )
    assert md.best == seq.best


@pytest.mark.parametrize("lb", ["lb1", "lb1_d"])
def test_pfsp_multi_fixed_incumbent_parity(lb):
    """With the incumbent seeded at the optimum the pruned tree is
    partition/steal-order independent: counts must match sequential exactly
    (the reference's ub=1 determinism invariant, SURVEY.md §4.2)."""
    ptm = T.reduced_instance(14, jobs=8, machines=5)
    opt = sequential_search(PFSPProblem(lb=lb, ub=0, p_times=ptm)).best
    seq = sequential_search(PFSPProblem(lb=lb, ub=0, p_times=ptm), initial_best=opt)
    md = multidevice_search(
        PFSPProblem(lb=lb, ub=0, p_times=ptm), m=5, M=64, D=4, initial_best=opt
    )
    assert md.best == opt
    assert md.explored_tree == seq.explored_tree
    assert md.explored_sol == seq.explored_sol


def test_multi_single_device_degenerate():
    """D=1: no victims, termination via the sticky flag on first idle."""
    ptm = T.reduced_instance(14, jobs=7, machines=5)
    seq = sequential_search(PFSPProblem(lb="lb1", ub=0, p_times=ptm))
    md = multidevice_search(PFSPProblem(lb="lb1", ub=0, p_times=ptm), m=5, M=64, D=1)
    assert md.best == seq.best


def test_workload_shares_sum_to_100():
    md = multidevice_search(NQueensProblem(N=9), m=10, M=256, D=4)
    shares = md.workload_shares()
    assert len(shares) == 4
    assert abs(sum(shares) - 100.0) < 1e-6
