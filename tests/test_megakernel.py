"""One-kernel resident cycle (ops/megakernel.py, TTS_MEGAKERNEL).

Interpret-mode bit-identity of the fused pop->eval->prune->compact->push
Pallas cycle against the fused-jnp resident across problem families,
compact modes, checkpoint cuts, and the batched engine; the lb2
bf16-exactness gate (bit-parity vs the f32 pair-blocked oracle on real
Taillard instances, refusal when the gate fails); and the program-cache
keying of the knob.  On CPU ``TTS_MEGAKERNEL=force`` arms the kernel in
Pallas interpret mode — same program structure, reference semantics —
so every claim here is about the real fused cycle body.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_tree_search.engine.batched import batched_search
from tpu_tree_search.engine.resident import resident_search
from tpu_tree_search.engine.sequential import sequential_search
from tpu_tree_search.ops import megakernel as MK
from tpu_tree_search.ops import pfsp_device as PD
from tpu_tree_search.problems import NQueensProblem, PFSPProblem


def _ptm(seed: int, jobs: int = 7, machines: int = 5) -> np.ndarray:
    rng = np.random.default_rng(seed)
    return np.ascontiguousarray(
        rng.integers(1, 100, size=(machines, jobs)).astype(np.int32)
    )


def _mk_problem(family: str):
    if family == "nqueens":
        return lambda: NQueensProblem(N=8)
    ptm = _ptm(311)
    lb = {"pfsp-lb1": "lb1", "pfsp-lb2": "lb2"}[family]
    return lambda: PFSPProblem(lb=lb, ub=0, p_times=ptm)


def _counts(res):
    return (res.explored_tree, res.explored_sol, res.best)


# -- force-vs-off bit identity across the family x compact matrix ----------

@pytest.mark.parametrize("family,compact", [
    ("nqueens", "auto"),
    ("nqueens", "dense"),
    ("nqueens", "scatter"),
    ("pfsp-lb1", "auto"),
    ("pfsp-lb1", "dense"),
    ("pfsp-lb1", "sort"),
    ("pfsp-lb2", "auto"),
    ("pfsp-lb2", "dense"),
    ("pfsp-lb2", "search"),
])
def test_force_matches_off_bit_identical(family, compact, monkeypatch):
    """The armed interpret-mode cycle lands the SAME explored_tree /
    explored_sol / best as the fused-jnp resident under every survivor
    compact mode (the off baseline varies; the fused cycle must not)."""
    monkeypatch.setenv("TTS_COMPACT", compact)
    mk = _mk_problem(family)
    monkeypatch.setenv("TTS_MEGAKERNEL", "0")
    off = resident_search(mk(), m=4, M=64, K=8)
    assert off.megakernel == "off"
    monkeypatch.setenv("TTS_MEGAKERNEL", "force")
    on = resident_search(mk(), m=4, M=64, K=8)
    assert on.megakernel == "on", on.megakernel_reason
    assert _counts(on) == _counts(off)


def test_force_matches_sequential_goldens(monkeypatch):
    """Armed counts against the host-recursion goldens directly (not just
    the off resident) — catches an error common to both device paths."""
    monkeypatch.setenv("TTS_MEGAKERNEL", "force")
    for family in ("nqueens", "pfsp-lb1", "pfsp-lb2"):
        mk = _mk_problem(family)
        opt = sequential_search(mk()).best
        seq = sequential_search(mk(), initial_best=opt)
        res = resident_search(mk(), m=4, M=64, K=8, initial_best=opt)
        assert res.megakernel == "on", res.megakernel_reason
        assert _counts(res) == _counts(seq)


# -- checkpoint cuts + the batched engine ----------------------------------

def _trajectory(mk, path):
    """Cut after every dispatch (max_steps=1, K=1) and resume until done;
    the per-slice counter trajectory is the strictest observable."""
    out = []
    res = resident_search(mk(), m=4, M=64, K=1, max_steps=1,
                          checkpoint_path=path)
    out.append(_counts(res) + (res.complete,))
    for _ in range(300):
        if res.complete:
            break
        res = resident_search(mk(), m=4, M=64, K=1, max_steps=1,
                              resume_from=path, checkpoint_path=path)
        out.append(_counts(res) + (res.complete,))
    assert res.complete
    return out


@pytest.mark.slow  # ~70 cut/resume program slices; CI tests-megakernel runs it unfiltered
def test_checkpoint_cut_resume_trajectory_matches(tmp_path, monkeypatch):
    """The armed cycle composes with checkpoint cuts: the full cut/resume
    trajectory (counters at EVERY slice boundary) is identical to the off
    build's — the megakernel changes where the work happens, never which
    state crosses a dispatch boundary."""
    ptm = _ptm(631, jobs=8)

    def mk():
        return PFSPProblem(lb="lb1", ub=0, p_times=ptm)

    monkeypatch.setenv("TTS_MEGAKERNEL", "0")
    t_off = _trajectory(mk, str(tmp_path / "off.ckpt"))
    monkeypatch.setenv("TTS_MEGAKERNEL", "force")
    t_on = _trajectory(mk, str(tmp_path / "on.ckpt"))
    assert t_on == t_off


@pytest.mark.parametrize("lb", ["lb1", "lb2"])
def test_batched_engine_armed_matches_sequential(lb, monkeypatch):
    """B=2 batched program with the fused cycle armed per slot: every
    tenant lands the sequential goldens and reports the armed state."""
    monkeypatch.setenv("TTS_MEGAKERNEL", "force")
    ptm = _ptm(911)

    def mk():
        return PFSPProblem(lb=lb, ub=0, p_times=ptm)

    opt = sequential_search(mk()).best
    seq = sequential_search(mk(), initial_best=opt)
    for res in batched_search(mk(), n_jobs=3, B=2, m=4, M=64, K=8,
                              initial_best=opt):
        assert res.megakernel == "on", res.megakernel_reason
        assert _counts(res) == _counts(seq)


def test_guard_green_armed(monkeypatch):
    """TTS_GUARD=1 runtime invariant checks stay green with the fused
    cycle armed (a guard trip raises)."""
    monkeypatch.setenv("TTS_GUARD", "1")
    monkeypatch.setenv("TTS_MEGAKERNEL", "force")
    mk = _mk_problem("pfsp-lb1")
    opt = sequential_search(mk()).best
    seq = sequential_search(mk(), initial_best=opt)
    res = resident_search(mk(), m=4, M=64, K=8, initial_best=opt)
    assert res.megakernel == "on"
    assert _counts(res) == _counts(seq)


# -- the lb2 bf16-exactness gate -------------------------------------------

@pytest.mark.parametrize("inst", [14, 21])
def test_lb2_bf16_mxu_bit_parity_on_taillard(inst):
    """The max-plus MXU formulation the megakernel arms with
    (``megakernel_lb2_bounds``, bf16 one-hot gathers) is BIT-equal to the
    f32 pair-blocked oracle (`pfsp_device._lb2_chunk`) on ta014/ta021
    nodes — on open slots (closed slots carry garbage both engines mask).
    If this ever fails, `resolve`'s exactness gate is wrong and the
    kernel must refuse to arm for the instance class."""
    prob = PFSPProblem(inst=inst, lb="lb2", ub=1)
    t = prob.device_tables()
    assert t.exact_bf16  # the gate resolve() checks before arming
    n = prob.jobs
    rng = np.random.default_rng(5 + inst)
    rows = 32
    prmu = np.stack([rng.permutation(n) for _ in range(rows)]).astype(np.int32)
    lim = rng.integers(-1, n - 2, size=rows).astype(np.int32)
    got = np.asarray(MK.megakernel_lb2_bounds(
        jnp.asarray(prmu), jnp.asarray(lim), t, interpret=True))
    pb = PD.lb2_pairblock(t.pairs.shape[0], n)
    want = np.asarray(PD._lb2_chunk(
        jnp.asarray(prmu), jnp.asarray(lim), t.ptm_t, t.min_heads,
        t.min_tails, t.pairs, t.lags, t.johnson_schedules, pairblock=pb))
    open_ = np.arange(n)[None, :] > lim[:, None]
    np.testing.assert_array_equal(got[open_], want[open_])


def test_lb2_bf16_gate_refuses_and_falls_back(monkeypatch):
    """Processing times >= 256 break bf16 exactness: even under force the
    resolver refuses, the run falls back to the fused-jnp resident
    bit-correct, and the SearchResult records why."""
    monkeypatch.setenv("TTS_MEGAKERNEL", "force")
    rng = np.random.default_rng(41)
    ptm = np.ascontiguousarray(
        rng.integers(200, 400, size=(4, 7)).astype(np.int32))
    ptm[0, 0] = 300  # guarantee the gate fails

    def mk():
        return PFSPProblem(lb="lb2", ub=0, p_times=ptm)

    opt = sequential_search(mk()).best
    seq = sequential_search(mk(), initial_best=opt)
    res = resident_search(mk(), m=4, M=64, K=8, initial_best=opt)
    assert res.megakernel == "off"
    assert res.megakernel_reason and "bf16" in res.megakernel_reason
    assert _counts(res) == _counts(seq)


def test_family_refusal_lb1d(monkeypatch):
    """lb1_d has no in-kernel bound formulation: force refuses with a
    recorded reason and the search still lands the goldens."""
    monkeypatch.setenv("TTS_MEGAKERNEL", "force")
    ptm = _ptm(311)

    def mk():
        return PFSPProblem(lb="lb1_d", ub=0, p_times=ptm)

    opt = sequential_search(mk()).best
    seq = sequential_search(mk(), initial_best=opt)
    res = resident_search(mk(), m=4, M=64, K=8, initial_best=opt)
    assert res.megakernel == "off"
    assert res.megakernel_reason
    assert _counts(res) == _counts(seq)


# -- program-cache keying ---------------------------------------------------

def test_knob_flip_rebuilds_and_reset_hits_cache(monkeypatch):
    """TTS_MEGAKERNEL is baked into the compiled step via the routing
    token: a flip rebuilds (distinct program objects), re-setting the
    original value hits the cache (same object)."""
    from tpu_tree_search.engine.resident import _make_program, resolve_capacity

    prob = NQueensProblem(N=8)
    dev = jax.devices()[0]
    monkeypatch.setenv("TTS_MEGAKERNEL", "0")
    capacity, M = resolve_capacity(prob, 64, None)
    a = _make_program(prob, 5, M, 4, capacity, dev)
    monkeypatch.setenv("TTS_MEGAKERNEL", "force")
    b = _make_program(prob, 5, M, 4, capacity, dev)
    assert a is not b
    assert b.megakernel.enabled and not a.megakernel.enabled
    monkeypatch.setenv("TTS_MEGAKERNEL", "0")
    c = _make_program(prob, 5, M, 4, capacity, dev)
    assert c is a  # cache hit — off really is the same program


def test_resolver_refusals_record_reasons():
    """Direct resolver checks: the correctness refusals hold even under
    force and each records a reason string."""
    dev = jax.devices()[0]
    ptm = _ptm(311)
    lb2 = PFSPProblem(lb="lb2", ub=0, p_times=ptm)
    # mp pair-axis sharding: the fused cycle is single-shard.
    d = MK.resolve(lb2, 64, dev, mp_axis="mp", mp_size=2)
    assert not d.enabled and "mp" in d.reason
    # chunk width must keep the sublane tiling exact.
    d = MK.resolve(NQueensProblem(N=8), 60, dev)
    assert not d.enabled and d.reason


# -- the streamed/tiled grid (TTS_MEGAKERNEL_MT) ----------------------------

@pytest.mark.parametrize("family", ["nqueens", "pfsp-lb1", "pfsp-lb2"])
def test_tiled_force_matches_off_bit_identical(family, monkeypatch):
    """A forced Mt=16 at M=64 streams the pool through a 4-step grid —
    per-tile dense compaction plus the SMEM-carried cross-tile offset
    (and the two-phase incumbent fold on PFSP) must land counts
    bit-identical to the off build, and the SearchResult must record the
    resolved tile width and the tiled state."""
    mk = _mk_problem(family)
    monkeypatch.setenv("TTS_MEGAKERNEL", "0")
    off = resident_search(mk(), m=4, M=64, K=8)
    monkeypatch.setenv("TTS_MEGAKERNEL", "force")
    monkeypatch.setenv("TTS_MEGAKERNEL_MT", "16")
    on = resident_search(mk(), m=4, M=64, K=8)
    assert on.megakernel == "on", on.megakernel_reason
    assert on.megakernel_mt == 16 and on.megakernel_tiled
    assert not off.megakernel_tiled and off.megakernel_mt is None
    assert _counts(on) == _counts(off)


@pytest.mark.slow  # ~70 cut/resume program slices; CI tests-megakernel runs it unfiltered
def test_tiled_checkpoint_cut_resume_trajectory_matches(tmp_path,
                                                        monkeypatch):
    """The streamed grid composes with checkpoint cuts: the full
    cut/resume counter trajectory under forced Mt=16 is identical to the
    off build's — the cross-tile carry lives and dies inside one cycle,
    never across a dispatch boundary."""
    ptm = _ptm(631, jobs=8)

    def mk():
        return PFSPProblem(lb="lb1", ub=0, p_times=ptm)

    monkeypatch.setenv("TTS_MEGAKERNEL", "0")
    t_off = _trajectory(mk, str(tmp_path / "off.ckpt"))
    monkeypatch.setenv("TTS_MEGAKERNEL", "force")
    monkeypatch.setenv("TTS_MEGAKERNEL_MT", "16")
    t_on = _trajectory(mk, str(tmp_path / "tiled.ckpt"))
    assert t_on == t_off


def test_auto_window_arms_tiled_past_limit(monkeypatch):
    """The pool size that used to be the auto refusal boundary now arms
    TILED: past the single-tile window the resolver streams the pool at a
    resolved Mt (multiple of 8, divides M) instead of refusing; inside
    the window the original single-tile form is kept verbatim.  The TPU
    backend gate is patched on — this is a decision-policy fact, not an
    execution one."""
    monkeypatch.setattr(MK, "_native_kind", lambda device=None: "tpu")
    prob = NQueensProblem(N=8)
    n = int(prob.child_slots)
    small = MK.resolve(prob, 64)
    assert small.enabled and small.auto
    assert small.grid == 1 and small.mt == 64
    M_big = 1 << 16
    assert M_big * n > MK.SMALL_M_LIMIT  # past the old refusal boundary
    d = MK.resolve(prob, M_big)
    assert d.enabled and d.auto, d.reason
    assert d.tiled and d.grid > 1
    assert d.mt % 8 == 0 and M_big % d.mt == 0
    assert d.grid == M_big // d.mt


def test_mt_misalignment_refuses_and_bad_value_raises(monkeypatch):
    """A tile width that does not divide M is a recorded refusal (the run
    falls back bit-correct), held even under force; a non-integer or
    non-positive width is an operator error and raises."""
    monkeypatch.setenv("TTS_MEGAKERNEL", "force")
    monkeypatch.setenv("TTS_MEGAKERNEL_MT", "24")  # %8 ok, 64 % 24 != 0
    mk = _mk_problem("pfsp-lb1")
    opt = sequential_search(mk()).best
    seq = sequential_search(mk(), initial_best=opt)
    res = resident_search(mk(), m=4, M=64, K=8, initial_best=opt)
    assert res.megakernel == "off"
    assert res.megakernel_reason and "divide" in res.megakernel_reason
    assert _counts(res) == _counts(seq)
    for bad in ("abc", "0", "-8"):
        monkeypatch.setenv("TTS_MEGAKERNEL_MT", bad)
        with pytest.raises(ValueError):
            MK.megakernel_mt()


def test_mt_knob_flip_rebuilds_and_reset_hits_cache(monkeypatch):
    """TTS_MEGAKERNEL_MT rides the routing token: under force a pinned
    width builds a DISTINCT program (tiled vs single-tile cycle bodies),
    and unsetting it again hits the original cached program."""
    from tpu_tree_search.engine.resident import _make_program, resolve_capacity

    prob = NQueensProblem(N=8)
    dev = jax.devices()[0]
    monkeypatch.setenv("TTS_MEGAKERNEL", "force")
    monkeypatch.delenv("TTS_MEGAKERNEL_MT", raising=False)
    capacity, M = resolve_capacity(prob, 64, None)
    a = _make_program(prob, 5, M, 4, capacity, dev)
    assert a.megakernel.enabled and a.megakernel.grid == 1
    monkeypatch.setenv("TTS_MEGAKERNEL_MT", "16")
    b = _make_program(prob, 5, M, 4, capacity, dev)
    assert a is not b
    assert b.megakernel.tiled and b.megakernel.mt == 16
    monkeypatch.delenv("TTS_MEGAKERNEL_MT", raising=False)
    c = _make_program(prob, 5, M, 4, capacity, dev)
    assert c is a  # cache hit — the unset-knob build really is the same


# -- the Megacore evaluation-only split -------------------------------------

def test_streamed_eval_bounds_matches_oracle():
    """The parallel-semantics evaluation pass: multi-tile output is
    bit-identical to single-tile (tile independence — the property that
    makes the Megacore split legal), and the lb1 plane matches the
    fused-jnp evaluator oracle on open slots."""
    ptm = _ptm(311)
    prob = PFSPProblem(lb="lb1", ub=0, p_times=ptm)
    n = prob.jobs
    rng = np.random.default_rng(7)
    B = 64
    prmu = np.stack([rng.permutation(n) for _ in range(B)]).astype(np.int32)
    lim = rng.integers(-1, n - 2, size=B).astype(np.int32)
    one = np.asarray(MK.streamed_eval_bounds(prob, prmu, lim, interpret=True))
    for mt in (8, 16, 32):
        tiled = np.asarray(MK.streamed_eval_bounds(
            prob, prmu, lim, mt=mt, interpret=True))
        np.testing.assert_array_equal(tiled, one)
    t = prob.device_tables()
    want = np.asarray(PD.lb1_bounds(
        jnp.asarray(prmu), jnp.asarray(lim), t))
    open_ = np.arange(n)[None, :] > lim[:, None]
    np.testing.assert_array_equal(one[open_], want[open_])
    # tile-width validation is an operator error, not a refusal
    with pytest.raises(ValueError):
        MK.streamed_eval_bounds(prob, prmu, lim, mt=24, interpret=True)
    # N-Queens label plane: tile independence on the other family shape.
    nq = NQueensProblem(N=8)
    board = rng.integers(0, 8, size=(B, nq.child_slots)).astype(np.int32)
    depth = rng.integers(0, 4, size=B).astype(np.int32)
    nq_one = np.asarray(MK.streamed_eval_bounds(
        nq, board, depth, interpret=True))
    nq_tiled = np.asarray(MK.streamed_eval_bounds(
        nq, board, depth, mt=16, interpret=True))
    np.testing.assert_array_equal(nq_tiled, nq_one)
    assert nq_one.shape == board.shape
