"""Device-tier parity tests (SURVEY.md §4 implication (b)): the chunked
single-device engine must reproduce the sequential engine's counts exactly
when the incumbent is fixed (N-Queens never prunes; PFSP ub=1 never improves
the incumbent), and the same optimum in all cases.

Runs on the CPU backend (conftest pins JAX_PLATFORMS=cpu) — the engine is
backend-agnostic; the driver exercises it on real TPU.
"""

import pytest

from tpu_tree_search.engine import sequential_search
from tpu_tree_search.engine.device import bucket_size, device_search
from tpu_tree_search.problems import NQueensProblem, PFSPProblem
from tpu_tree_search.problems.pfsp import taillard as T


def test_bucket_size():
    # Lower clamp: everything below m folds into the next_pow2(m) bucket.
    assert bucket_size(1, 25, 50000) == 32
    assert bucket_size(25, 25, 50000) == 32
    assert bucket_size(33, 25, 50000) == 64
    assert bucket_size(50000, 25, 50000) == 50000
    assert bucket_size(70000, 25, 50000) == 50000


def test_pad_chunk_pads_to_bucket():
    from tpu_tree_search.engine.device import pad_chunk
    import numpy as np

    snap = {"x": np.arange(10, dtype=np.int32), "y": np.ones((10, 3), np.int8)}
    padded = pad_chunk(snap, 10, 16)
    assert padded["x"].shape == (16,)
    assert padded["y"].shape == (16, 3)
    assert (padded["x"][10:] == snap["x"][0]).all()
    exact = pad_chunk(snap, 10, 10)
    assert exact["x"].shape == (10,)


@pytest.mark.parametrize("n", [8, 10])
@pytest.mark.parametrize("overlap", [False, True])
def test_nqueens_device_matches_sequential(n, overlap):
    seq = sequential_search(NQueensProblem(N=n))
    dev = device_search(NQueensProblem(N=n), m=25, M=1024, overlap=overlap)
    assert dev.explored_sol == seq.explored_sol
    assert dev.explored_tree == seq.explored_tree


def test_nqueens_device_g_knob():
    dev1 = device_search(NQueensProblem(N=8, g=1), m=25, M=512)
    dev3 = device_search(NQueensProblem(N=8, g=3), m=25, M=512)
    assert (dev1.explored_tree, dev1.explored_sol) == (
        dev3.explored_tree,
        dev3.explored_sol,
    )


@pytest.mark.parametrize("lb", ["lb1", "lb1_d", "lb2"])
def test_pfsp_device_finds_optimum_ub0(lb):
    ptm = T.reduced_instance(14, jobs=7, machines=5)
    seq = sequential_search(PFSPProblem(lb=lb, ub=0, p_times=ptm))
    dev = device_search(PFSPProblem(lb=lb, ub=0, p_times=ptm), m=10, M=256)
    assert dev.best == seq.best


@pytest.mark.parametrize("lb", ["lb1", "lb1_d", "lb2"])
def test_pfsp_device_matches_sequential_with_fixed_incumbent(lb):
    """With the incumbent seeded at the optimum it never improves, so the
    pruned tree is order-independent and counts must match exactly (the
    reference's ub=1 determinism invariant, SURVEY.md §4.2)."""
    ptm = T.reduced_instance(14, jobs=8, machines=5)
    opt = sequential_search(PFSPProblem(lb=lb, ub=0, p_times=ptm)).best
    seq = sequential_search(PFSPProblem(lb=lb, ub=0, p_times=ptm), initial_best=opt)
    dev = device_search(
        PFSPProblem(lb=lb, ub=0, p_times=ptm), m=10, M=128, initial_best=opt
    )
    assert dev.best == seq.best == opt
    assert dev.explored_tree == seq.explored_tree
    assert dev.explored_sol == seq.explored_sol


def test_pfsp_device_diagnostics_counted():
    ptm = T.reduced_instance(14, jobs=7, machines=5)
    dev = device_search(PFSPProblem(lb="lb1", ub=0, p_times=ptm), m=10, M=256)
    d = dev.diagnostics
    assert d.kernel_launches > 0
    assert d.host_to_device == d.kernel_launches
    assert d.device_to_host == d.kernel_launches


def test_offload_staged_lb2_parity(monkeypatch):
    """The offload evaluator's staged lb2 (where(cand, self_lb2, lb1)) must
    reproduce the single-pass run node-for-node: lb1-dead children report
    lb1 >= dispatch-time best, which the host prunes identically since its
    running best only tightens."""
    ptm = T.reduced_instance(14, jobs=10, machines=5)
    opt = sequential_search(PFSPProblem(lb="lb2", ub=0, p_times=ptm)).best

    monkeypatch.setenv("TTS_LB2_STAGED", "0")
    base = device_search(
        PFSPProblem(lb="lb2", ub=0, p_times=ptm), m=8, M=256, initial_best=opt
    )
    monkeypatch.setenv("TTS_LB2_STAGED", "1")
    staged = device_search(
        PFSPProblem(lb="lb2", ub=0, p_times=ptm), m=8, M=256, initial_best=opt
    )
    assert (staged.explored_tree, staged.explored_sol, staged.best) == (
        base.explored_tree, base.explored_sol, base.best
    )

    # Improving incumbent: the host tightens best inside chunks.
    monkeypatch.setenv("TTS_LB2_STAGED", "0")
    base2 = device_search(PFSPProblem(lb="lb2", ub=0, p_times=ptm), m=8, M=256)
    monkeypatch.setenv("TTS_LB2_STAGED", "1")
    staged2 = device_search(PFSPProblem(lb="lb2", ub=0, p_times=ptm), m=8, M=256)
    assert (staged2.explored_tree, staged2.explored_sol, staged2.best) == (
        base2.explored_tree, base2.explored_sol, base2.best
    )
