"""Cost-model profiles (obs/costmodel.py): span->fit aggregation on
synthetic spans, COSTMODEL.json persistence, AdaptiveK band resolution
from a profile (deterministic), and bit-identical search results vs the
fixed-band fallback."""

from __future__ import annotations

import json

import pytest

from tpu_tree_search.obs import costmodel as cm
from tpu_tree_search.problems import NQueensProblem, PFSPProblem


def _span(name, dur, wid=0, host=0, **args):
    return {"name": name, "cat": "tts", "ph": "X", "ts": 0.0, "dur": dur,
            "pid": host, "tid": wid, "args": args}


def _dispatch_events(latency_us=8000.0, per_cycle_us=25.0, n=20):
    """Synthetic dispatch spans with an exact known latency+slope — the
    deterministic stand-in for the simulated-latency harness's injected
    round trip (tests/test_pipeline.py injects it with sleeps; here the
    model is exact so the fit recovery can be asserted to tolerance)."""
    return [
        _span("dispatch", latency_us + per_cycle_us * c, cycles=c)
        for c in range(1, n + 1)
    ]


# -- span -> fit aggregation -------------------------------------------------


def test_fit_recovers_latency_and_bandwidth():
    fit = cm.fit_link([(c, 8000.0 + 25.0 * c) for c in range(1, 21)])
    assert fit["n"] == 20
    assert fit["latency_us"] == pytest.approx(8000.0, abs=1.0)
    assert fit["per_unit_us"] == pytest.approx(25.0, abs=0.01)
    assert fit["per_sec"] == pytest.approx(1e6 / 25.0, rel=0.01)
    assert fit["p50_us"] <= fit["p90_us"] <= fit["p99_us"]


def test_fit_trims_compile_spike():
    # One 760 ms compile outlier among 10 ms steady-state spans must not
    # poison the intercept (the observed first-dispatch failure mode).
    samples = [(c, 8000.0 + 25.0 * c) for c in range(1, 20)]
    samples.append((1, 760_000.0))
    fit = cm.fit_link(samples)
    assert fit["latency_us"] == pytest.approx(8000.0, abs=100.0)
    assert fit["p99_us"] > 100_000.0  # ...but the percentile shows it


def test_fit_degenerate_cases():
    assert cm.fit_link([]) is None
    one = cm.fit_link([(4.0, 100.0)])
    assert one["latency_us"] == 100.0 and one["per_unit_us"] is None
    flat = cm.fit_link([(4.0, 100.0), (4.0, 120.0), (4.0, 110.0)])
    assert flat["latency_us"] == 110.0  # no x spread: median latency
    assert flat["per_unit_us"] is None


def test_samples_from_events_buckets_link_classes():
    evts = (
        _dispatch_events(n=3)
        + [_span("chunk", 500.0, count=128),
           _span("exchange", 900.0, round=1),
           _span("donate_send", 1500.0, nodes=64, bytes=4096),
           _span("donate_recv", 1800.0, nodes=64, bytes=4096),
           _span("checkpoint", 123.0),  # unrecognized: ignored
           {"name": "exchange", "ph": "i", "ts": 0.0, "pid": 0, "tid": 0}]
    )
    links = cm.samples_from_events(evts)
    assert set(links) == {"dispatch", "offload", "exchange", "donate"}
    assert len(links["dispatch"]) == 3
    assert links["offload"] == [(128.0, 500.0)]
    assert links["exchange"] == [(0.0, 900.0)]  # latency-only class
    assert sorted(links["donate"]) == [(4096.0, 1500.0), (4096.0, 1800.0)]


def test_shape_class_and_keys():
    assert cm.shape_class(NQueensProblem(N=12)) == "nqueens_n12"
    p = PFSPProblem(inst=14, lb="lb1", ub=1)
    assert cm.shape_class(p) == f"pfsp_j{p.jobs}x{p.machines}_lb1"
    assert cm.shape_class(None) == "any"
    assert cm.profile_key("tpu", "device-D1", "nqueens_n12") == \
        "tpu|device-D1|nqueens_n12"


# -- persistence -------------------------------------------------------------


def test_build_save_load_merge(tmp_path):
    path = str(tmp_path / "COSTMODEL.json")
    p1 = cm.build_profile(_dispatch_events(), "cpu", "device-D1", "a")
    cm.save(path, p1)
    p2 = cm.build_profile(_dispatch_events(latency_us=100.0), "cpu",
                          "mesh-D4", "b")
    merged = cm.save(path, p2)
    assert set(merged) == {"cpu|device-D1|a", "cpu|mesh-D4|b"}
    loaded = cm.load(path)
    assert loaded == merged
    assert loaded["cpu|device-D1|a"]["links"]["dispatch"]["latency_us"] \
        == pytest.approx(8000.0, abs=1.0)
    # Corrupt file: load degrades to None, save starts fresh over it.
    (tmp_path / "bad.json").write_text("{ truncated")
    assert cm.load(str(tmp_path / "bad.json")) is None
    cm.save(str(tmp_path / "bad.json"), p1)
    assert cm.load(str(tmp_path / "bad.json")) is not None


def test_lookup_degradation_order():
    prof = {
        "tpu|device-D1|shapeA": {"backend": "tpu", "topology": "device-D1",
                                 "shape": "shapeA", "links": {}},
        "tpu|mesh-D4|shapeB": {"backend": "tpu", "topology": "mesh-D4",
                               "shape": "shapeB", "links": {}},
        "cpu|device-D1|shapeA": {"backend": "cpu", "topology": "device-D1",
                                 "shape": "shapeA", "links": {}},
    }
    assert cm.lookup(prof, "tpu", "device-D1", "shapeA")[0] == \
        "tpu|device-D1|shapeA"
    # Same backend+shape on another topology beats other shapes.
    assert cm.lookup(prof, "tpu", "mesh-D8", "shapeB")[0] == \
        "tpu|mesh-D4|shapeB"
    # Same backend only: deterministic (sorted) fallback.
    assert cm.lookup(prof, "tpu", "x", "zzz")[0] == "tpu|device-D1|shapeA"
    assert cm.lookup(prof, "gpu", "x", "shapeA") is None


# -- band resolution ---------------------------------------------------------


def _entry(latency_us):
    return {"links": {"dispatch": {"latency_us": latency_us, "n": 20}}}


def test_resolve_band_reproduces_fixed_bands_at_design_point():
    """The formula's anchor: at the 8 ms assumed round trip the measured
    bands equal the documented fixed defaults exactly."""
    from tpu_tree_search.engine.pipeline import MESH_TARGET, RESIDENT_TARGET

    assert cm.resolve_band(_entry(8000.0), "resident") == RESIDENT_TARGET
    assert cm.resolve_band(_entry(8000.0), "mesh") == MESH_TARGET
    assert cm.resolve_band(_entry(8000.0), "dist_mesh") == MESH_TARGET


def test_resolve_band_scales_and_clamps():
    # The tunnel regime: 360 ms round trips want second-scale dispatches.
    lo, hi = cm.resolve_band(_entry(360_000.0), "resident")
    assert lo == pytest.approx(2.0)  # clamped at the 2 s cap
    assert hi == pytest.approx(5.0)
    # A fast local link: bands shrink but never below the floor.
    lo, hi = cm.resolve_band(_entry(10.0), "resident")
    assert lo == pytest.approx(0.020) and hi == pytest.approx(0.050)
    # No usable dispatch fit: callers keep the fixed band.
    assert cm.resolve_band({"links": {}}, "resident") is None
    assert cm.resolve_band(_entry(0.0), "resident") is None


def test_resolve_target_band_via_env(tmp_path, monkeypatch):
    """engine/pipeline.resolve_target_band: TTS_COSTMODEL arms the
    measured band deterministically; unset/corrupt keeps the default."""
    from tpu_tree_search.engine.pipeline import (
        RESIDENT_TARGET,
        resolve_target_band,
    )

    prob = NQueensProblem(N=10)
    monkeypatch.delenv("TTS_COSTMODEL", raising=False)
    assert resolve_target_band("resident", RESIDENT_TARGET, prob) == \
        (RESIDENT_TARGET, None)
    # A profile with a 64 ms measured latency: band = (0.8, 2.0) exactly.
    path = str(tmp_path / "COSTMODEL.json")
    prof = cm.build_profile(
        _dispatch_events(latency_us=64_000.0), "cpu", "device-D1",
        cm.shape_class(prob),
    )
    cm.save(path, prof)
    monkeypatch.setenv("TTS_COSTMODEL", path)
    band, src = resolve_target_band(
        "resident", RESIDENT_TARGET, prob, topology="device-D1"
    )
    assert src == f"cpu|device-D1|{cm.shape_class(prob)}"
    assert band == (pytest.approx(0.8), pytest.approx(2.0))
    assert band != RESIDENT_TARGET
    # Corrupt profile: silent fixed-band fallback, never an error.
    (tmp_path / "junk.json").write_text("not json")
    monkeypatch.setenv("TTS_COSTMODEL", str(tmp_path / "junk.json"))
    assert resolve_target_band("resident", RESIDENT_TARGET, prob) == \
        (RESIDENT_TARGET, None)
    monkeypatch.setenv("TTS_COSTMODEL", "0")
    assert resolve_target_band("resident", RESIDENT_TARGET, prob) == \
        (RESIDENT_TARGET, None)


def test_profile_changes_adaptive_k_band_with_bit_identical_results(
        tmp_path, monkeypatch):
    """The acceptance criterion: a COSTMODEL.json produced from measured
    spans changes AdaptiveK's resolved band deterministically, with
    bit-identical search results vs the fixed-band fallback."""
    from tpu_tree_search.engine.resident import resident_search
    from tpu_tree_search.engine.sequential import sequential_search
    from tpu_tree_search.obs import events

    monkeypatch.setenv("TTS_K", "auto")
    monkeypatch.delenv("TTS_COSTMODEL", raising=False)
    seq = sequential_search(NQueensProblem(N=9))
    baseline = resident_search(NQueensProblem(N=9), m=8, M=128, K=8)
    # Build the profile from a REAL traced run (the simulated-latency
    # harness regime: CPU spans; the fit is whatever was measured) but pin
    # the dispatch latency afterwards so the band assertion is exact.
    monkeypatch.setenv("TTS_OBS", "host")
    events.reset()
    resident_search(NQueensProblem(N=9), m=8, M=128, K=8)
    prof = cm.build_profile(events.drain(), "cpu", "device-D1",
                            cm.shape_class(NQueensProblem(N=9)))
    key = next(iter(prof))
    assert prof[key]["links"]["dispatch"]["n"] >= 2  # real spans landed
    prof[key]["links"]["dispatch"]["latency_us"] = 64_000.0
    path = str(tmp_path / "COSTMODEL.json")
    cm.save(path, prof)
    monkeypatch.delenv("TTS_OBS", raising=False)

    monkeypatch.setenv("TTS_COSTMODEL", path)
    events.reset()
    monkeypatch.setenv("TTS_OBS", "host")
    profiled = resident_search(NQueensProblem(N=9), m=8, M=128, K=8)
    evts = events.drain()
    bands = [e for e in evts if e.get("name") == "costmodel"]
    assert bands and bands[0]["args"]["source"] == key
    assert bands[0]["args"]["lo_ms"] == pytest.approx(800.0)
    assert bands[0]["args"]["hi_ms"] == pytest.approx(2000.0)
    # Bit-identical counts vs both the fixed-band run and sequential.
    assert (profiled.explored_tree, profiled.explored_sol) == \
        (baseline.explored_tree, baseline.explored_sol) == \
        (seq.explored_tree, seq.explored_sol)
    assert profiled.k_auto


# -- CLI capture (--costmodel) -----------------------------------------------


def test_cli_costmodel_capture(tmp_path, monkeypatch, capsys):
    from tpu_tree_search import cli

    monkeypatch.delenv("TTS_OBS", raising=False)
    path = str(tmp_path / "COSTMODEL.json")
    assert cli.main([
        "nqueens", "--N", "8", "--tier", "device", "--m", "5", "--M", "64",
        "--costmodel", path,
    ]) == 0
    out = capsys.readouterr().out
    assert "Cost model written" in out and "dispatch" in out
    doc = json.load(open(path))
    key = "cpu|device-D1|nqueens_n8"
    assert key in doc
    assert doc[key]["links"]["dispatch"]["n"] >= 1


def test_exchange_sleep_from_profile():
    entry = {"links": {"exchange": {"p50_us": 30_000.0}}}
    assert cm.exchange_sleep_s(entry) == pytest.approx(0.06)
    assert cm.exchange_sleep_s({"links": {}}) is None
    # Capped: a pathological fit cannot park an idle host for seconds.
    assert cm.exchange_sleep_s(
        {"links": {"exchange": {"p50_us": 10_000_000.0}}}
    ) == pytest.approx(0.5)
