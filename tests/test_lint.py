"""Unit tests for the `tts lint` static-analysis framework (ISSUE 1).

Fixture-based: each rule has a known-bad snippet under tests/data/lint/
that must produce its findings at the expected lines, and a known-good
snippet that must stay silent. The repo itself must lint clean against the
committed baseline — with *empty* cells for the resident hot paths."""

from __future__ import annotations

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

import tpu_tree_search
from tpu_tree_search import cli
from tpu_tree_search.analysis import DEFAULT_BASELINE, lint
from tpu_tree_search.analysis.baseline import load_baseline, ratchet, save_baseline
from tpu_tree_search.analysis.core import RULES

FIXTURES = Path(__file__).parent / "data" / "lint"
PKG = Path(tpu_tree_search.__file__).parent
REPO = PKG.parent


def findings_of(path, rule=None):
    res = lint([str(path)])
    out = res["new"]
    if rule is not None:
        out = [f for f in out if f.rule == rule]
    return out


def test_all_rules_registered():
    assert {"host-sync-in-jit", "tracer-branch", "guarded-by",
            "static-arg-hygiene", "lock-order"} <= set(RULES)


# -- host-sync-in-jit ------------------------------------------------------


def test_host_sync_bad_fixture():
    fs = findings_of(FIXTURES / "bad_host_sync.py", "host-sync-in-jit")
    lines = sorted(f.line for f in fs)
    # .item() in a decorated jit; float() via call closure; np.asarray in a
    # while_loop body; device_get + block_until_ready in a jit-bound fn;
    # int() in a marker-annotated traced fn.
    assert lines == [12, 16, 21, 34, 35, 44]
    msgs = " ".join(f.message for f in fs)
    assert ".item()" in msgs and "numpy.asarray" in msgs
    assert "jax.device_get" in msgs and ".block_until_ready()" in msgs


# -- tracer-branch ---------------------------------------------------------


def test_tracer_branch_bad_fixture():
    fs = findings_of(FIXTURES / "bad_tracer_branch.py", "tracer-branch")
    lines = sorted(f.line for f in fs)
    # line 39: a static_argnames param REBOUND from a traced value is
    # re-tainted — the static exemption is per-name seed, not a blanket.
    assert lines == [9, 12, 23, 39]
    # the static-shape `if` (line 15) and the `is None` check (line 26)
    # must NOT be flagged
    assert 15 not in lines and 26 not in lines


# -- guarded-by ------------------------------------------------------------


def test_guarded_by_bad_fixture():
    fs = findings_of(FIXTURES / "bad_guarded_by.py", "guarded-by")
    lines = sorted(f.line for f in fs)
    assert lines == [29, 34, 35, 42, 44]


def test_guarded_by_waiver_honored():
    res = lint([str(FIXTURES / "bad_guarded_by.py")])
    waived = [f for f in res["waived"] if f.rule == "guarded-by"]
    assert len(waived) == 1 and waived[0].line == 49


# -- lock-order (ISSUE 8: the acquisition-order audit) ---------------------


def test_lock_order_bad_fixture():
    fs = findings_of(FIXTURES / "bad_lock_order.py", "lock-order")
    lines = sorted(f.line for f in fs)
    # line 29 closes the A->B->A blocking cycle; line 35 blocking-acquires
    # a same-class sibling; the try_lock probe (line 41) is sanctioned.
    assert lines == [29, 35]
    msgs = " ".join(f.message for f in fs)
    assert "cycle" in msgs and "try_lock" in msgs


# -- static-arg-hygiene ----------------------------------------------------


def test_static_arg_bad_fixture():
    fs = findings_of(FIXTURES / "bad_static_args.py", "static-arg-hygiene")
    assert len(fs) == 3
    msgs = " ".join(f.message for f in fs)
    assert "'m'" in msgs and "'flip'" in msgs and "'k'" in msgs
    # the declared-static param must not be flagged
    assert "partial_ok" not in msgs


# -- known-good fixture ----------------------------------------------------


def test_good_fixture_is_clean():
    assert findings_of(FIXTURES / "good_clean.py") == []


# -- waiver format ---------------------------------------------------------


def test_stale_waiver_is_a_finding(tmp_path):
    """ISSUE 8 satellite: a waiver whose rule runs but no longer fires on
    its line is flagged (it would silently disarm the rule for future
    edits); a waiver for a rule the run did not select is left alone, and
    a waiver naming an unknown rule is always stale."""
    f = tmp_path / "s.py"
    f.write_text(
        "x = 1  # tts-lint: waive tracer-branch -- long-fixed\n"
        "y = 2  # tts-lint: waive no-such-rule -- typo'd rule name\n"
    )
    res = lint([str(f)])
    stale = [x for x in res["new"] if x.rule == "waiver-stale"]
    assert sorted(x.line for x in stale) == [1, 2]
    assert "unknown rule" in stale[1].message
    # rule-subset runs cannot judge unselected rules: only the unknown-rule
    # waiver is stale there
    res2 = lint([str(f)], rules=["guarded-by"])
    stale2 = [x for x in res2["new"] if x.rule == "waiver-stale"]
    assert [x.line for x in stale2] == [2]




def test_waiver_without_reason_is_a_finding(tmp_path):
    f = tmp_path / "w.py"
    f.write_text(
        "import threading\n\n\n"
        "class C:\n"
        "    # guarded-by: lock -- x\n"
        "    def __init__(self):\n"
        "        self.lock = threading.Lock()\n"
        "        self.x = 0\n\n\n"
        "def f(c: C):\n"
        "    # tts-lint: waive guarded-by\n"
        "    return c.x\n"
    )
    res = lint([str(f)])
    rules = {x.rule for x in res["new"]}
    # the reasonless waiver is flagged AND does not suppress the finding
    assert "waiver-format" in rules and "guarded-by" in rules


# -- baseline ratchet ------------------------------------------------------


def test_baseline_ratchet(tmp_path):
    bad = FIXTURES / "bad_tracer_branch.py"
    res = lint([str(bad)])
    assert len(res["new"]) == 4
    bl = tmp_path / "bl.json"
    save_baseline(str(bl), res["new"])
    counts = load_baseline(str(bl))
    res2 = lint([str(bad)], counts)
    assert res2["new"] == [] and len(res2["baselined"]) == 4
    # shrinking the accepted count resurfaces the whole cell
    cell = next(iter(counts))
    counts[cell] -= 1
    new, old = ratchet(res["new"], counts)
    assert len(new) == 4 and old == []


def test_repo_lints_clean_with_committed_baseline():
    """ONE full-package run asserting the three repo-level bars (a full
    lint pays the shared type-inference pass — don't run it thrice):
    clean vs the committed baseline, zero lock-order findings (the
    acceptance bar: the steal/exchange/checkpoint paths carry no blocking
    acquisition cycle), and zero stale waivers (every committed waiver
    still suppresses a live finding)."""
    baseline = load_baseline(str(REPO / DEFAULT_BASELINE))
    res = lint([str(PKG)], baseline)
    assert res["new"] == [], "\n".join(f.render() for f in res["new"])
    assert [f for f in res["baselined"] if f.rule == "lock-order"] == []
    assert len(res["waived"]) >= 8  # the audited justified waivers


def test_hot_path_baseline_cells_are_empty():
    """ISSUE 1 satellite: engine/resident.py and parallel/resident_mesh.py
    must lint clean with NO baseline debt."""
    counts = load_baseline(str(REPO / DEFAULT_BASELINE))
    dirty = [
        cell for cell in counts
        if "engine/resident.py" in cell or "parallel/resident_mesh.py" in cell
    ]
    assert dirty == []


# -- CLI surfaces ----------------------------------------------------------


def test_cli_lint_bad_fixture_nonzero():
    rc = cli.main(["lint", "--no-baseline",
                   str(FIXTURES / "bad_host_sync.py")])
    assert rc == 1


def test_cli_lint_repo_zero(monkeypatch):
    monkeypatch.chdir(REPO)
    assert cli.main(["lint"]) == 0


def test_cli_lint_json(capsys):
    rc = cli.main(["lint", "--no-baseline", "--json",
                   str(FIXTURES / "bad_static_args.py")])
    assert rc == 1
    out = json.loads(capsys.readouterr().out)
    assert len(out["new"]) == 3


def test_module_entry_point():
    proc = subprocess.run(
        [sys.executable, "-m", "tpu_tree_search.analysis", "--no-baseline",
         str(FIXTURES / "bad_guarded_by.py")],
        capture_output=True, text=True,
        cwd=str(REPO), env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 1
    assert "guarded-by" in proc.stdout


def test_update_baseline_roundtrip(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    bad = FIXTURES / "bad_tracer_branch.py"
    bl = tmp_path / "bl.json"
    assert cli.main(["lint", "--baseline", str(bl), "--update-baseline",
                     str(bad)]) == 0
    assert cli.main(["lint", "--baseline", str(bl), str(bad)]) == 0


@pytest.mark.parametrize("rule", ["host-sync-in-jit", "tracer-branch",
                                  "guarded-by", "static-arg-hygiene"])
def test_rule_selection(rule):
    res = lint([str(FIXTURES)], rules=[rule])
    assert all(f.rule in (rule, "waiver-format") for f in res["new"])
    assert any(f.rule == rule for f in res["new"])
