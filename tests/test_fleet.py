"""Fleet router (tpu_tree_search/fleet/): class-aware placement, the
lifecycle proxy, failure-driven recovery, and the seeded load generator.

The placement policy is pure functions over synthetic daemon snapshots —
those tests never open a socket. The end-to-end tests run real
in-process daemons (port 0) behind an in-process router; only the
SIGKILL-recovery test needs a subprocess daemon (you cannot SIGKILL a
thread). Everything runs on the virtual CPU platform with small shapes.
"""

from __future__ import annotations

import json
import os
import re
import signal
import subprocess
import sys
import time
import urllib.request

import pytest

from tpu_tree_search.fleet import loadgen, placement
from tpu_tree_search.fleet.placement import DaemonState
from tpu_tree_search.fleet.router import FleetJobMap, FleetRouter
from tpu_tree_search.serve.server import ServeDaemon

_FINAL = ("done", "failed", "cancelled")

#: The warm-placement shape shared across e2e tests (same reasoning as
#: test_serve.NQ10: distinct shapes multiply CPU compiles).
NQ10 = {"problem": "nqueens", "N": 10, "M": 256}


def _post(base, path, payload):
    req = urllib.request.Request(
        base + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode())


def _get(base, path):
    try:
        with urllib.request.urlopen(base + path, timeout=30) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode())


def _wait_final(router_url, fid, timeout_s=180.0):
    """Poll the router until the fleet job is terminal AND fresh (a
    cached record mid-recovery reports ``stale``)."""
    deadline = time.monotonic() + timeout_s
    rec = None
    while time.monotonic() < deadline:
        code, rec = _get(router_url, f"/job/{fid}")
        assert code == 200, rec
        if rec["state"] in _FINAL and not rec.get("stale"):
            return rec
        time.sleep(0.1)
    raise AssertionError(f"fleet job {fid} not final in {timeout_s}s: {rec}")


def _daemon(tmp_path, name, **kw):
    d = ServeDaemon(port=0, state_dir=str(tmp_path / name), **kw)
    d.start()
    return d


def _router(tmp_path, daemons, **kw):
    kw.setdefault("scrape_interval_s", 0.2)
    kw.setdefault("pull_interval_s", 0.3)
    r = FleetRouter(port=0, state_dir=str(tmp_path / "fleet"),
                    daemons=[d.url for d in daemons], **kw)
    r.start()
    return r


# -- the pure placement policy (no sockets) ----------------------------------


def _state(url, *, healthy=True, draining=False, queue_depth=0,
           classes=(), jobs=(), wait_sum=0.0, wait_count=0):
    st = DaemonState(url)
    st.healthy = healthy
    st.draining = draining
    st.health = {"ok": healthy, "queue_depth": queue_depth}
    st.classes = list(classes)
    st.jobs = list(jobs)
    st.metrics = {
        "tts_serve_queue_wait_seconds_sum": {(): wait_sum},
        "tts_serve_queue_wait_seconds_count": {(): wait_count},
    }
    return st


def test_choose_prefers_warm_class():
    warm = _state("http://a:1", queue_depth=3,
                  classes=[{"class": "X", "warm": True}])
    idle = _state("http://b:1", queue_depth=0)
    st, reason = placement.choose([idle, warm], "X")
    # Warm beats idle even though the warm daemon is busier: admission
    # there costs queue time, admission elsewhere costs a compile.
    assert st is warm and reason == "warm"


def test_choose_warm_free_slot_beats_warm_busy():
    busy = _state("http://a:1", classes=[
        {"class": "X", "warm": True, "batch_slots": 2, "slots_occupied": 2}])
    free = _state("http://b:1", classes=[
        {"class": "X", "warm": True, "batch_slots": 2, "slots_occupied": 1}])
    st, reason = placement.choose([busy, free], "X")
    assert st is free and reason == "warm"


def test_choose_cold_goes_least_loaded():
    hot = _state("http://a:1", queue_depth=4)
    cool = _state("http://b:1", queue_depth=1)
    waity = _state("http://c:1", queue_depth=1, wait_sum=40.0, wait_count=4)
    st, reason = placement.choose([hot, cool, waity], "Y")
    # Same queue depth on b and c, but c's measured mean queue wait
    # (10 s) adds 50 points — the cold job warms on b.
    assert st is cool and reason == "cold"


def test_choose_skips_unhealthy_and_draining():
    dead = _state("http://a:1", healthy=False,
                  classes=[{"class": "X", "warm": True}])
    drain = _state("http://b:1", draining=True,
                   classes=[{"class": "X", "warm": True}])
    up = _state("http://c:1")
    st, reason = placement.choose([dead, drain, up], "X")
    assert st is up and reason == "cold"
    st, why = placement.choose([dead, drain], "X")
    assert st is None and "no healthy daemon" in why


def test_pick_rebalance_hot_to_idle():
    hot = _state("http://a:1", queue_depth=3, jobs=[
        {"id": "job-1", "state": "running", "checkpoint": "x", "steps": 50},
        {"id": "job-2", "state": "running", "checkpoint": "y", "steps": 90},
        {"id": "job-3", "state": "running", "checkpoint": None, "steps": 99},
    ])
    idle = _state("http://b:1", queue_depth=0)
    got = placement.pick_rebalance([hot, idle], min_depth=2)
    assert got is not None
    src, job, dst = got
    # The longest-running CHECKPOINTED job moves (job-3 has more steps
    # but no cut to carry).
    assert src is hot and dst is idle and job["id"] == "job-2"
    # Below the depth threshold, or with the idle daemon busy: no move.
    hot.health["queue_depth"] = 1
    assert placement.pick_rebalance([hot, idle], min_depth=2) is None
    hot.health["queue_depth"] = 3
    idle.jobs = [{"id": "j", "state": "running"}]
    assert placement.pick_rebalance([hot, idle], min_depth=2) is None


# -- the load generator (pure) -----------------------------------------------


def test_make_plan_deterministic_and_heavy_tailed():
    p1 = loadgen.make_plan(seed=42, n_jobs=200, rate_per_s=10.0)
    p2 = loadgen.make_plan(seed=42, n_jobs=200, rate_per_s=10.0)
    assert p1 == p2, "same seed must yield the identical plan"
    p3 = loadgen.make_plan(seed=43, n_jobs=200, rate_per_s=10.0)
    assert p1 != p3
    ats = [row["at_s"] for row in p1]
    assert ats == sorted(ats) and len(ats) == 200
    steps = [row["spec"]["max_steps"] for row in p1]
    assert all(8 <= s <= 600 for s in steps)
    # Heavy tail: the cap actually binds somewhere in 200 draws, and the
    # median sits far below the max (Pareto alpha=1.5).
    assert max(steps) > 10 * sorted(steps)[len(steps) // 2]
    classes = {loadgen._class_of(row["spec"]) for row in p1}
    assert len(classes) == len(loadgen.DEFAULT_CLASSES)


def test_quantile_nearest_rank():
    assert loadgen._quantile([], 0.99) == 0.0
    assert loadgen._quantile([5.0], 0.99) == 5.0
    xs = list(range(100))
    assert loadgen._quantile(xs, 0.50) == 50
    assert loadgen._quantile(xs, 0.99) == 98


# -- the host-only pin -------------------------------------------------------


def test_router_is_host_only(monkeypatch):
    """TTS_ROUTER must never fork a compiled-program cache key, and the
    fleet package must never import jax — the router places work, it
    does not compute."""
    from tpu_tree_search.serve.pool import server_env_token

    monkeypatch.delenv("TTS_ROUTER", raising=False)
    t0 = server_env_token()
    monkeypatch.setenv("TTS_ROUTER", "http://127.0.0.1:9999")
    assert server_env_token() == t0, \
        "TTS_ROUTER leaked into the server env token (a cache-key fork)"
    import tpu_tree_search.fleet as fleet_pkg

    pkg_dir = os.path.dirname(fleet_pkg.__file__)
    for name in sorted(os.listdir(pkg_dir)):
        if not name.endswith(".py"):
            continue
        with open(os.path.join(pkg_dir, name)) as f:
            src = f.read()
        assert not re.search(r"^\s*(import jax|from jax)", src, re.M), \
            f"fleet/{name} imports jax — the router must stay host-only"


def test_fleet_job_map_durable(tmp_path):
    m1 = FleetJobMap(str(tmp_path))
    job = m1.create({"problem": "nqueens"}, "clsX")
    m1.update(job, daemon="http://a:1", daemon_job="job-000007",
              ckpt_steps=12)
    m2 = FleetJobMap(str(tmp_path))
    assert m2.load() == 1
    back = m2.get(job.id)
    assert back.daemon == "http://a:1" and back.daemon_job == "job-000007"
    assert back.ckpt_steps == 12 and back.cls == "clsX"
    # The sequence resumes past reloaded ids — no id reuse after restart.
    assert int(m2.create({}, "c").id.split("-")[-1]) > \
        int(job.id.split("-")[-1])


# -- end-to-end: placement, proxy, streams -----------------------------------


def test_fleet_warm_placement_zero_recompiles(tmp_path, monkeypatch):
    """The acceptance E2E: three mixed-class jobs through a two-daemon
    fleet. The second same-class job must land on the warm daemon and
    admit with zero recompiles (TTS_GUARD=1 makes any hidden compile
    fatal); the different-class job must spill to the other daemon."""
    monkeypatch.setenv("TTS_GUARD", "1")
    da = _daemon(tmp_path, "a")
    db = _daemon(tmp_path, "b")
    r = _router(tmp_path, [da, db])
    try:
        code, p1 = _post(r.url, "/submit", {**NQ10, "max_steps": 40})
        assert code == 201 and p1["placement"] == "cold", p1
        rec1 = _wait_final(r.url, p1["id"])
        assert rec1["state"] == "done"
        time.sleep(0.8)  # one keeper scrape refreshes /classes
        code, p2 = _post(r.url, "/submit", {**NQ10, "max_steps": 40})
        assert code == 201 and p2["placement"] == "warm", p2
        assert p2["daemon"] == p1["daemon"], "warm job missed its daemon"
        code, p3 = _post(r.url, "/submit",
                         {"problem": "nqueens", "N": 9, "M": 256,
                          "max_steps": 40})
        assert code == 201 and p3["placement"] == "cold", p3
        assert p3["daemon"] != p1["daemon"], \
            "cold class should warm on the less-loaded daemon"
        rec2 = _wait_final(r.url, p2["id"])
        rec3 = _wait_final(r.url, p3["id"])
        assert rec2["state"] == "done" and rec3["state"] == "done"
        assert rec2["new_programs"] == 0 and \
            rec2["new_step_compiles"] == 0, \
            f"warm-placed job recompiled: {rec2}"
        # Fleet-id rewrite: the proxied record answers with the fleet
        # identity, the daemon-local id rides along.
        assert rec2["id"] == p2["id"] and rec2["daemon_job"].startswith("job-")
        code, fleet = _get(r.url, "/fleet")
        assert fleet["router"]["daemons_healthy"] == 2
        assert {j["state"] for j in fleet["jobs"]} == {"done"}
    finally:
        r.close()
        for d in (da, db):
            d.scheduler.drain(timeout_s=30.0)
            d.close()


def test_fleet_sse_stream_proxy(tmp_path):
    """The proxied per-job stream ends with a ``done`` frame whose
    payload carries the FLEET identity (that's the frame clients act
    on), relayed from the owning daemon."""
    da = _daemon(tmp_path, "a")
    r = _router(tmp_path, [da])
    try:
        code, p = _post(r.url, "/submit", {**NQ10, "max_steps": 40})
        assert code == 201, p
        fid = p["id"]
        done = None
        with urllib.request.urlopen(r.url + f"/job/{fid}/stream",
                                    timeout=120) as resp:
            event = None
            for raw in resp:
                line = raw.decode().rstrip("\n")
                if line.startswith("event: "):
                    event = line[len("event: "):]
                elif line.startswith("data: ") and event == "done":
                    done = json.loads(line[len("data: "):])
                    break
        assert done is not None, "stream closed without a done frame"
        assert done["id"] == fid and done["state"] == "done"
        assert done["daemon_job"].startswith("job-")
        assert done["daemon"] == da.url
    finally:
        r.close()
        da.scheduler.drain(timeout_s=30.0)
        da.close()


def test_fleet_top_once_json(tmp_path, capsys):
    """`tts top --router URL --once --json` emits the /fleet aggregate
    as one JSON line (the CI smoke mode)."""
    from tpu_tree_search.serve.client import fleet_top_main

    da = _daemon(tmp_path, "a")
    r = _router(tmp_path, [da])
    try:
        rc = fleet_top_main(r.url, once=True, as_json=True)
        assert rc == 0
        payload = json.loads(capsys.readouterr().out.strip())
        assert payload["router"]["daemons"] == 1
        assert payload["daemons"][0]["url"] == da.url
        assert payload["daemons"][0]["healthy"] is True
    finally:
        r.close()
        da.scheduler.drain(timeout_s=30.0)
        da.close()


def test_fleet_rejects_bad_spec_and_no_capacity(tmp_path):
    da = _daemon(tmp_path, "a")
    r = _router(tmp_path, [da])
    try:
        code, resp = _post(r.url, "/submit", {"problem": "tsp"})
        assert code == 400 and "error" in resp
        code, resp = _get(r.url, "/job/fjob-999999")
        assert code == 404
    finally:
        r.close()
        da.scheduler.drain(timeout_s=30.0)
        da.close()
    # With its only daemon gone (scrapes fail), placement must 503, not
    # hang or 500 — the error names the reason.
    r2 = FleetRouter(port=0, state_dir=str(tmp_path / "fleet2"),
                     daemons=[da.url], scrape_interval_s=0.2)
    r2.start()
    try:
        code, resp = _post(r2.url, "/submit", dict(NQ10))
        assert code == 503 and "no daemon" in resp["error"]
    finally:
        r2.close()


# -- end-to-end: failure-driven recovery -------------------------------------


def test_sigkill_recovery_bit_identical(tmp_path):
    """The headline guarantee: SIGKILL a daemon mid-job; the router
    resubmits the last pulled checkpoint cut (with the remaining
    ``max_steps`` budget) to a daemon registered afterwards, and the
    final counters equal a standalone uninterrupted run's, exactly."""
    from tpu_tree_search.engine.resident import resident_search
    from tpu_tree_search.problems import NQueensProblem

    ref = resident_search(NQueensProblem(N=12), m=25, M=256, K=4)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("TTS_GUARD", None)  # the subprocess compiles cold by design
    pa = subprocess.Popen(
        [sys.executable, "-m", "tpu_tree_search.cli", "serve", "--port",
         "0", "--state-dir", str(tmp_path / "a"), "--ckpt-every", "0.3"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env)
    db = None
    r = None
    try:
        url_a = None
        for line in pa.stdout:
            m = re.search(r"(http://127\.0\.0\.1:\d+)", line)
            if m:
                url_a = m.group(1)
                break
        assert url_a, "daemon A never printed its banner"
        r = _router(tmp_path, [], max_misses=2)
        r.register(url_a)
        code, p = _post(r.url, "/submit",
                        {"problem": "nqueens", "N": 12, "M": 256, "K": 4})
        assert code == 201, p
        fid = p["id"]
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            fj = r.jobs.get(fid)
            if fj.ckpt and fj.ckpt_steps > 0:
                break
            time.sleep(0.1)
        assert fj.ckpt, "router never pulled a checkpoint cut"
        pa.send_signal(signal.SIGKILL)
        db = _daemon(tmp_path, "b")
        time.sleep(0.8)  # let the death detector flag A's jobs first
        r.register(db.url)
        rec = _wait_final(r.url, fid)
        assert rec["state"] == "done" and rec["daemon"] == db.url
        assert rec["resubmits"] >= 1
        res = rec["result"]
        assert res["explored_tree"] == ref.explored_tree
        assert res["explored_sol"] == ref.explored_sol
        assert res["best"] == ref.best
    finally:
        if r is not None:
            r.close()
        if db is not None:
            db.scheduler.drain(timeout_s=30.0)
            db.close()
        pa.kill()
        pa.wait(timeout=30)


def test_drain_triggers_live_migration(tmp_path):
    """A draining daemon's ``/healthz`` flags it; the keeper migrates
    its jobs to a healthy daemon over the live (cancel-with-cut) path
    and the result still matches an uninterrupted run."""
    from tpu_tree_search.engine.resident import resident_search
    from tpu_tree_search.problems import NQueensProblem

    ref = resident_search(NQueensProblem(N=11), m=25, M=256, K=4)
    da = _daemon(tmp_path, "a", ckpt_every_s=0.3)
    db = _daemon(tmp_path, "b")
    r = _router(tmp_path, [da, db])
    try:
        code, p = _post(r.url, "/submit",
                        {"problem": "nqueens", "N": 11, "M": 256, "K": 4})
        assert code == 201, p
        # Wait for the first slice to actually start on A, then drain A.
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            code, rec = _get(r.url, f"/job/{p['id']}")
            if rec.get("state") == "running":
                break
            time.sleep(0.1)
        da.scheduler.drain(timeout_s=0.0)
        rec = _wait_final(r.url, p["id"])
        assert rec["state"] == "done" and rec["daemon"] == db.url
        res = rec["result"]
        assert res["explored_tree"] == ref.explored_tree
        assert res["explored_sol"] == ref.explored_sol
        assert res["best"] == ref.best
    finally:
        r.close()
        for d in (da, db):
            d.scheduler.drain(timeout_s=30.0)
            d.close()


@pytest.mark.slow
def test_loadgen_saturation_point(tmp_path):
    """One saturation point end-to-end: the loadgen drives a 2-daemon
    fleet and every admitted job finishes with a measured queue wait.
    (The full ladder is bench.py fleet_sat; this pins the plumbing.)"""
    da = _daemon(tmp_path, "a")
    db = _daemon(tmp_path, "b")
    r = _router(tmp_path, [da, db])
    try:
        plan = loadgen.make_plan(seed=5, n_jobs=6, rate_per_s=2.0,
                                 steps_scale=10, steps_cap=40)
        res = loadgen.run_plan(r.url, plan, timeout_s=300.0)
        s = res["summary"]
        assert s["offered"] == 6 and s["admitted"] == 6, s
        assert s["done"] == 6, (s, res["jobs"])
        assert s["queue_wait_ms_p99"] >= s["queue_wait_ms_p50"] >= 0
        assert res["per_class"], "per-class breakdown missing"
    finally:
        r.close()
        for d in (da, db):
            d.scheduler.drain(timeout_s=30.0)
            d.close()
