"""Checkpoint/resume: an interrupted search resumed from its snapshot must
land on exactly the sequential goldens (the frontier + incumbent + counters
are the complete search state). The reference has no such subsystem
(SURVEY.md §5) — these tests pin down ours.
"""

from __future__ import annotations

import pytest

from tpu_tree_search.engine import checkpoint as ckpt
from tpu_tree_search.engine.resident import resident_search
from tpu_tree_search.engine.sequential import sequential_search
from tpu_tree_search.parallel.resident_mesh import mesh_resident_search
from tpu_tree_search.problems import NQueensProblem, PFSPProblem
from tpu_tree_search.problems.pfsp import taillard


def test_resident_interrupt_resume(tmp_path):
    path = str(tmp_path / "nq.ckpt")
    prob = NQueensProblem(N=11)
    seq = sequential_search(prob)
    # Small M + K force many dispatches; cut off after 2 and checkpoint.
    part = resident_search(
        prob, m=8, M=64, K=2, max_steps=2, checkpoint_path=path
    )
    assert not part.complete
    assert part.explored_tree < seq.explored_tree
    done = resident_search(prob, m=8, M=64, K=2, resume_from=path)
    assert done.complete
    assert (done.explored_tree, done.explored_sol) == (
        seq.explored_tree,
        seq.explored_sol,
    )


def test_mesh_interrupt_resume_changing_shards(tmp_path):
    import jax

    path = str(tmp_path / "pfsp.ckpt")
    ptm = taillard.reduced_instance(14, jobs=10, machines=5)
    opt = sequential_search(PFSPProblem(lb="lb1", ub=0, p_times=ptm)).best
    seq = sequential_search(PFSPProblem(lb="lb1", ub=0, p_times=ptm), initial_best=opt)
    part = mesh_resident_search(
        PFSPProblem(lb="lb1", ub=0, p_times=ptm),
        m=8, M=64, K=2, initial_best=opt,
        max_steps=1, checkpoint_path=path,
    )
    assert not part.complete
    # Resume on a different shard count (single device): the frontier
    # re-partitions, counts must still match exactly.
    done = mesh_resident_search(
        PFSPProblem(lb="lb1", ub=0, p_times=ptm),
        m=8, M=64, K=2, devices=jax.devices()[:1], resume_from=path,
    )
    assert done.complete
    assert (done.explored_tree, done.explored_sol, done.best) == (
        seq.explored_tree,
        seq.explored_sol,
        opt,
    )


def test_checkpoint_refuses_wrong_problem(tmp_path):
    path = str(tmp_path / "x.ckpt")
    prob = NQueensProblem(N=9)
    resident_search(prob, m=8, M=64, K=2, max_steps=1, checkpoint_path=path)
    with pytest.raises(ValueError, match="checkpoint is for"):
        ckpt.load(path, NQueensProblem(N=10))
    with pytest.raises(ValueError, match="checkpoint is for"):
        ckpt.load(path, PFSPProblem(inst=14))
