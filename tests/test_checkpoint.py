"""Checkpoint/resume: an interrupted search resumed from its snapshot must
land on exactly the sequential goldens (the frontier + incumbent + counters
are the complete search state). The reference has no such subsystem
(SURVEY.md §5) — these tests pin down ours.
"""

from __future__ import annotations

import pytest

from tpu_tree_search.engine import checkpoint as ckpt
from tpu_tree_search.engine.resident import resident_search
from tpu_tree_search.engine.sequential import sequential_search
from tpu_tree_search.parallel.resident_mesh import mesh_resident_search
from tpu_tree_search.problems import NQueensProblem, PFSPProblem
from tpu_tree_search.problems.pfsp import taillard


def test_resident_interrupt_resume(tmp_path):
    path = str(tmp_path / "nq.ckpt")
    prob = NQueensProblem(N=11)
    seq = sequential_search(prob)
    # Small M + K force many dispatches; cut off after 2 and checkpoint.
    part = resident_search(
        prob, m=8, M=64, K=2, max_steps=2, checkpoint_path=path
    )
    assert not part.complete
    assert part.explored_tree < seq.explored_tree
    done = resident_search(prob, m=8, M=64, K=2, resume_from=path)
    assert done.complete
    assert (done.explored_tree, done.explored_sol) == (
        seq.explored_tree,
        seq.explored_sol,
    )


def test_mesh_interrupt_resume_changing_shards(tmp_path):
    import jax

    path = str(tmp_path / "pfsp.ckpt")
    ptm = taillard.reduced_instance(14, jobs=10, machines=5)
    opt = sequential_search(PFSPProblem(lb="lb1", ub=0, p_times=ptm)).best
    seq = sequential_search(PFSPProblem(lb="lb1", ub=0, p_times=ptm), initial_best=opt)
    part = mesh_resident_search(
        PFSPProblem(lb="lb1", ub=0, p_times=ptm),
        m=8, M=64, K=2, initial_best=opt,
        max_steps=1, checkpoint_path=path,
    )
    assert not part.complete
    # Resume on a different shard count (single device): the frontier
    # re-partitions, counts must still match exactly.
    done = mesh_resident_search(
        PFSPProblem(lb="lb1", ub=0, p_times=ptm),
        m=8, M=64, K=2, devices=jax.devices()[:1], resume_from=path,
    )
    assert done.complete
    assert (done.explored_tree, done.explored_sol, done.best) == (
        seq.explored_tree,
        seq.explored_sol,
        opt,
    )


def test_checkpoint_refuses_wrong_problem(tmp_path):
    path = str(tmp_path / "x.ckpt")
    prob = NQueensProblem(N=9)
    resident_search(prob, m=8, M=64, K=2, max_steps=1, checkpoint_path=path)
    with pytest.raises(ValueError, match="checkpoint is for"):
        ckpt.load(path, NQueensProblem(N=10))
    with pytest.raises(ValueError, match="checkpoint is for"):
        ckpt.load(path, PFSPProblem(inst=14))


def test_checkpoint_refuses_different_ptimes(tmp_path):
    """Two ad-hoc instances with identical (jobs, machines) but different
    processing times must not resume each other (ADVICE r1: meta needs a
    p_times digest, not just shapes)."""
    import numpy as np

    path = str(tmp_path / "adhoc.ckpt")
    ptm_a = taillard.reduced_instance(14, jobs=6, machines=4)
    ptm_b = np.ascontiguousarray(ptm_a.copy())
    ptm_b[0, 0] += 1
    prob_a = PFSPProblem(lb="lb1", ub=0, p_times=ptm_a)
    prob_b = PFSPProblem(lb="lb1", ub=0, p_times=ptm_b)
    batch = prob_a.root()
    ckpt.save(path, prob_a, batch, best=10**9, tree=0, sol=0)
    ckpt.load(path, prob_a)  # same instance: fine
    with pytest.raises(ValueError, match="checkpoint is for"):
        ckpt.load(path, prob_b)


def test_checkpoint_accepts_v1_when_meta_matches(tmp_path):
    """v1 NQueens checkpoints (meta = N/g, fully identifying) must resume;
    every v1 PFSP file is refused — v1-era writers stamped the default inst
    even for ad-hoc matrices, so a v1 meta claiming a named instance may
    belong to a different p_times matrix entirely (code-review r4)."""
    import json

    import numpy as np

    def save_as_v1(path, problem, batch):
        meta = {k: v for k, v in ckpt.problem_meta(problem).items()
                if k != "ptimes_sha"}
        header = {
            "version": 1, "meta": meta, "best": 10**9, "tree": 5, "sol": 1,
            "fields": sorted(batch.keys()),
        }
        arrays = {f"field_{k}": v for k, v in batch.items()}
        with open(path, "wb") as f:
            np.savez_compressed(
                f,
                header=np.frombuffer(
                    json.dumps(header).encode(), dtype=np.uint8
                ),
                **arrays,
            )

    qpath = str(tmp_path / "v1q.ckpt")
    qprob = NQueensProblem(N=9)
    save_as_v1(qpath, qprob, qprob.root())
    assert ckpt.load(qpath, qprob).tree == 5
    with pytest.raises(ValueError, match="checkpoint is for"):
        ckpt.load(qpath, NQueensProblem(N=10))

    # Every v1 PFSP checkpoint is refused — named instances included: the
    # v1 meta cannot prove which matrix produced the frontier.
    prob = PFSPProblem(inst=14)
    path = str(tmp_path / "v1.ckpt")
    save_as_v1(path, prob, prob.root())
    with pytest.raises(ValueError, match="v1 PFSP"):
        ckpt.load(path, prob)

    apath = str(tmp_path / "v1adhoc.ckpt")
    ptm = taillard.reduced_instance(14, jobs=6, machines=4)
    aprob = PFSPProblem(lb="lb1", ub=0, p_times=ptm)
    save_as_v1(apath, aprob, aprob.root())
    with pytest.raises(ValueError, match="v1 PFSP"):
        ckpt.load(apath, aprob)


def test_committed_v1_fixture_resumes():
    """The committed v1 fixture (tests/data/nqueens_n9_v1.ckpt.npz — a real
    interrupted N=9 resident run rewritten to the v1 header, wide-int32
    depth) must keep loading and resuming to the sequential goldens under
    every future format bump: cross-version compatibility pinned by a file
    on disk, not by a writer that evolves with the reader."""
    import os

    path = os.path.join(os.path.dirname(__file__), "data",
                        "nqueens_n9_v1.ckpt.npz")
    prob = NQueensProblem(N=9)
    c = ckpt.load(path, prob)
    assert c.tree == 734 and c.sol == 0
    # Loader casts the v1 wide payload to the live storage dtypes.
    fields = prob.node_fields()
    for k, v in c.batch.items():
        assert v.dtype == fields[k][1]
    seq = sequential_search(prob)
    done = resident_search(prob, m=8, M=64, K=2, resume_from=path)
    assert done.complete
    assert (done.explored_tree, done.explored_sol, done.best) == (
        seq.explored_tree, seq.explored_sol, seq.best)


@pytest.mark.parametrize("writer,reader", [("auto", "0"), ("0", "auto")])
def test_cross_narrow_resume_bit_identical(tmp_path, monkeypatch,
                                           writer, reader):
    """A checkpoint written under one TTS_NARROW setting resumed under the
    other must reproduce the uninterrupted sequential goldens exactly —
    the npz is self-describing and the loader casts to the live dtypes,
    so narrow<->wide files are interchangeable bit-for-bit."""
    path = str(tmp_path / f"x{writer}{reader}.ckpt")
    ptm = taillard.reduced_instance(14, jobs=8, machines=5)

    def fresh():
        return PFSPProblem(lb="lb1", ub=0, p_times=ptm)

    # Pin the incumbent so explored counts are order-independent (same
    # discipline as the mesh resume test above).
    opt = sequential_search(fresh()).best
    seq = sequential_search(fresh(), initial_best=opt)
    monkeypatch.setenv("TTS_NARROW", writer)
    part = resident_search(fresh(), m=8, M=64, K=2, initial_best=opt,
                           max_steps=2, checkpoint_path=path)
    assert not part.complete
    monkeypatch.setenv("TTS_NARROW", reader)
    prob = fresh()
    c = ckpt.load(path, prob)
    fields = prob.node_fields()
    for k, v in c.batch.items():
        assert v.dtype == fields[k][1]
    done = resident_search(prob, m=8, M=64, K=2, resume_from=path)
    assert done.complete
    assert (done.explored_tree, done.explored_sol, done.best) == (
        seq.explored_tree, seq.explored_sol, opt)


def test_resolve_capacity_grows_for_chunk_floor():
    """A tiny explicit capacity must grow to fit the 64-chunk floor rather
    than leave M*n > capacity/2, which would starve the device loop and
    silently run everything through the host-offload fallback (ADVICE r1)."""
    from tpu_tree_search.engine.resident import resolve_capacity

    prob = NQueensProblem(N=12)
    capacity, M = resolve_capacity(prob, M=50000, capacity=256)
    assert M >= 64
    assert 2 * M * prob.child_slots <= capacity


def test_cli_rejects_mesh_offload_and_stray_perc(capsys):
    from tpu_tree_search import cli

    with pytest.raises(SystemExit):
        cli.main(["nqueens", "--tier", "mesh", "--engine", "offload"])
    assert "resident-only" in capsys.readouterr().err
    with pytest.raises(SystemExit):
        cli.main(["nqueens", "--tier", "seq", "--perc", "0.3"])
    assert "--perc only applies" in capsys.readouterr().err


def test_large_instance_checkpoint_resume(tmp_path):
    """Interrupt/resume on a 50-job instance: counters continue and the
    frontier survives the round trip."""
    path = str(tmp_path / "ta031.ckpt")
    prob = PFSPProblem(inst=31, lb="lb1", ub=1)
    part = resident_search(
        prob, m=25, M=1024, K=2, max_steps=2, checkpoint_path=path
    )
    assert not part.complete
    saved = ckpt.load(path, PFSPProblem(inst=31, lb="lb1", ub=1))
    assert saved.tree == part.explored_tree
    assert saved.batch["prmu"].shape[1] == 50
    res = resident_search(
        PFSPProblem(inst=31, lb="lb1", ub=1),
        m=25, M=1024, K=2, max_steps=2, resume_from=path,
    )
    assert res.explored_tree > part.explored_tree


def test_multi_tier_checkpoint_resume(tmp_path):
    """Multi-device tier: periodic chunk-boundary checkpoints during a full
    run, then a resume from the mid-run snapshot reaches exactly the
    sequential goldens (N-Queens has no pruning, so tree/sol totals are
    schedule-independent). Also proves the format is tier-agnostic: the
    same file resumes on the resident (device) tier."""
    import os

    from tpu_tree_search.parallel.multidevice import multidevice_search

    path = str(tmp_path / "multi.ckpt")
    prob = NQueensProblem(N=10)
    seq = sequential_search(prob)
    full = multidevice_search(
        prob, m=5, M=256, D=2, checkpoint_path=path,
        checkpoint_interval_s=0.05,
    )
    assert (full.explored_tree, full.explored_sol) == (
        seq.explored_tree, seq.explored_sol
    )
    assert os.path.exists(path), "no checkpoint fired during the run"
    saved = ckpt.load(path, NQueensProblem(N=10))
    assert saved.tree <= seq.explored_tree

    resumed = multidevice_search(
        NQueensProblem(N=10), m=5, M=256, D=2, resume_from=path
    )
    assert (resumed.explored_tree, resumed.explored_sol) == (
        seq.explored_tree, seq.explored_sol
    )

    # Cross-tier: the multi checkpoint resumes on the resident engine.
    res_dev = resident_search(NQueensProblem(N=10), m=8, M=256, resume_from=path)
    assert (res_dev.explored_tree, res_dev.explored_sol) == (
        seq.explored_tree, seq.explored_sol
    )


def test_dist_tier_checkpoint_resume(tmp_path):
    """Dist tier (2 virtual hosts): per-host files cut in the same
    communicator round; resuming both hosts reaches the sequential
    goldens."""
    import os

    from tpu_tree_search.parallel.dist import dist_search

    path = str(tmp_path / "dist.ckpt")
    prob = NQueensProblem(N=10)
    seq = sequential_search(prob)
    full = dist_search(
        prob, m=5, M=256, D=1, num_hosts=2, steal_interval_s=0.005,
        checkpoint_path=path, checkpoint_interval_s=0.02,
    )
    assert (full.explored_tree, full.explored_sol) == (
        seq.explored_tree, seq.explored_sol
    )
    assert os.path.exists(path + ".h0") and os.path.exists(path + ".h1"), (
        "per-host checkpoints did not fire"
    )
    # A per-host file refuses to resume into a different host count (it
    # would silently drop the other hosts' shares).
    with pytest.raises(ValueError, match="per-host files"):
        ckpt.load(path + ".h0", NQueensProblem(N=10))

    resumed = dist_search(
        NQueensProblem(N=10), m=5, M=256, D=1, num_hosts=2,
        steal_interval_s=0.005, resume_from=path,
    )
    assert (resumed.explored_tree, resumed.explored_sol) == (
        seq.explored_tree, seq.explored_sol
    )


def test_dist_resume_refuses_mismatched_cuts(tmp_path):
    """Per-host files from DIFFERENT cuts (a host crashing between the
    two-phase-commit allgather and its os.replace, or stale files from a
    prior run with the same host count) pass the hosts check but describe an
    incoherent frontier union — nodes donated between the two rounds would
    be lost or double-explored (ADVICE r4 medium). Resume must allgather the
    cut tags and refuse on mismatch; matched tags (the happy path) are
    covered by test_dist_tier_checkpoint_resume."""
    import json

    import numpy as np

    from tpu_tree_search.parallel.dist import dist_search

    path = str(tmp_path / "dist.ckpt")
    prob = NQueensProblem(N=10)
    dist_search(
        prob, m=5, M=256, D=1, num_hosts=2, steal_interval_s=0.005,
        checkpoint_path=path, checkpoint_interval_s=0.0,
    )
    tags = []
    for h in (0, 1):
        with np.load(path + f".h{h}") as data:
            header = json.loads(bytes(data["header"]).decode())
        # Multi-host per-host files write the higher format version (v4
        # since narrow storage) so pre-v3 readers (no hosts/cut checks)
        # refuse them instead of resuming one host's share as the whole
        # frontier (ADVICE r4).
        assert header["version"] == ckpt.FORMAT_VERSION == 4
        assert header["hosts"] == 2
        tags.append(header["cut_tag"])
    # Lockstep cut: the SAME "<run-uuid>:<round>" tag on every host.
    assert tags[0] == tags[1] and tags[0] is not None
    assert ":" in str(tags[0])

    # Tamper host 1's file to impersonate a different cut of another run.
    loaded = ckpt.load(path + ".h1", NQueensProblem(N=10), expect_hosts=2)
    ckpt.save(path + ".h1", prob, loaded.batch, loaded.best, loaded.tree,
              loaded.sol, hosts=2, cut_tag="deadbeef0000:999")
    with pytest.raises(ValueError, match="incoherent multi-host resume"):
        dist_search(
            NQueensProblem(N=10), m=5, M=256, D=1, num_hosts=2,
            steal_interval_s=0.005, resume_from=path,
        )
