// tts_native — C++ host runtime for tpu_tree_search.
//
// The reference implements its host path in C (pools: baselines/*/lib/Pool.c,
// bounds: baselines/pfsp/lib/c_bound_simple.c / c_bound_johnson.c, drivers:
// baselines/*/*.c). This library is the TPU framework's native equivalent:
// the host-side search primitives that surround the JAX/XLA device kernels —
// BFS warm-up, DFS drain, full sequential search, and the prune/branch
// consumption of device results (generate_children).
//
// It is NOT a translation of the reference C. Structural differences:
//   * pools are struct-of-arrays deques (contiguous per-field buffers that
//     cross the ctypes boundary as numpy arrays, no per-node marshalling),
//     not arrays of node structs;
//   * child bounds are computed incrementally from a once-per-parent state
//     (front/remain/fixed-set) in O(m) per child, instead of re-scanning the
//     whole prefix per child the way the reference's lb1_bound does
//     (c_bound_simple.c:143-158 re-runs schedule_front for every child);
//   * the per-instance lb tables (min_heads/min_tails, Johnson schedules,
//     lags, machine pairs) are built once in Python (bounds.py — the
//     framework's semantic oracle) and passed in, so every tier of the
//     framework shares bit-identical tables.
//
// Counting/traversal parity: all loops visit children in ascending slot
// order and stacks pop from the back, exactly like the Python engines, so
// exploredTree/exploredSol/makespan match the golden tables for every
// (problem, lb, ub) configuration.

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// SoA node deques.  pop_front serves BFS warm-up, pop_back serves DFS;
// storage compacts lazily once the consumed prefix dominates.
// ---------------------------------------------------------------------------

template <typename T>
class SoaDeque {
 public:
  explicit SoaDeque(size_t row_width) : width_(row_width) {}

  size_t size() const { return count_; }
  size_t width() const { return width_; }

  void reserve_rows(size_t rows) { data_.reserve((start_ + count_ + rows) * width_); }

  // Append one row, returning a pointer to its storage for in-place fill.
  T* emplace_back() {
    maybe_compact();
    data_.resize((start_ + count_ + 1) * width_);
    ++count_;
    return &data_[(start_ + count_ - 1) * width_];
  }

  // Pop newest; pointer valid until the next mutation.
  const T* pop_back() {
    if (count_ == 0) return nullptr;
    --count_;
    return &data_[(start_ + count_) * width_];
  }

  // Pop oldest; pointer valid until the next mutation.
  const T* pop_front() {
    if (count_ == 0) return nullptr;
    const T* row = &data_[start_ * width_];
    ++start_;
    --count_;
    return row;
  }

  const T* row(size_t i) const { return &data_[(start_ + i) * width_]; }

 private:
  void maybe_compact() {
    if (start_ > 1024 && start_ >= count_) {
      std::memmove(data_.data(), data_.data() + start_ * width_,
                   count_ * width_ * sizeof(T));
      data_.resize(count_ * width_);
      start_ = 0;
    }
  }

  size_t width_;
  size_t start_ = 0;
  size_t count_ = 0;
  std::vector<T> data_;
};

// ---------------------------------------------------------------------------
// N-Queens
// ---------------------------------------------------------------------------

struct NqPool {
  explicit NqPool(int n) : depth(1), board(static_cast<size_t>(n)) {}
  SoaDeque<int32_t> depth;
  SoaDeque<uint8_t> board;
};

// Diagonal-safety of placing `row` as queen number `depth`.  The g-round
// repetition is the reference's artificial workload knob (--g); the compiler
// barrier keeps the redundant rounds from being folded away.
inline bool nq_is_safe(const uint8_t* board, int depth, int row, int g) {
  bool safe = true;
  for (int round = 0; round < g; ++round) {
    bool ok = true;
    for (int i = 0; i < depth; ++i) {
      const int other = board[i];
      const int gap = depth - i;
      ok &= (other != row - gap) & (other != row + gap);
    }
    safe = ok;
    asm volatile("" ::: "memory");
  }
  return safe;
}

// Expand one node onto the pool.  Returns children pushed; bumps *sol for a
// depth==N leaf.  Child order: ascending candidate slot (parity with the
// Python tier's j-ascending loop).
//
// For n <= 32 the parent's two diagonal occupancy masks are built once
// (O(depth)) and each child checks in O(1) — bit b of diag1 marks an
// occupied row-i+n anti-diagonal, bit b of diag2 a row+i diagonal; the
// per-child predicate is exactly nq_is_safe's (rows are distinct by the
// permutation invariant), so the explored tree is bit-identical. The
// g-round workload knob repeats the masked check with the same compiler
// barrier the scalar path uses.
int64_t nq_expand(NqPool& pool, int n, int g, int32_t depth,
                  const uint8_t* board, int64_t* sol) {
  if (depth == n) {
    ++*sol;
    return 0;
  }
  int64_t pushed = 0;
  uint64_t diag1 = 0, diag2 = 0;
  const bool masks = n <= 32;
  if (masks) {
    for (int i = 0; i < depth; ++i) {
      diag1 |= 1ull << (board[i] - i + n);
      diag2 |= 1ull << (board[i] + i);
    }
  }
  for (int j = depth; j < n; ++j) {
    if (masks) {
      const int row = board[j];
      bool safe = true;
      for (int round = 0; round < g; ++round) {
        // The barrier must clobber the REGISTER inputs: a plain "memory"
        // clobber would let LICM hoist this pure register arithmetic and
        // turn the --g workload knob into a no-op on the fast path.
        asm volatile("" : "+r"(diag1), "+r"(diag2));
        safe = !(((diag1 >> (row - depth + n)) |
                  (diag2 >> (row + depth))) & 1ull);
      }
      if (!safe) continue;
    } else if (!nq_is_safe(board, depth, board[j], g)) continue;
    *pool.depth.emplace_back() = depth + 1;
    uint8_t* child = pool.board.emplace_back();
    std::memcpy(child, board, static_cast<size_t>(n));
    child[depth] = board[j];
    child[j] = board[depth];
    ++pushed;
  }
  return pushed;
}

void nq_seed(NqPool& pool, int n, const int32_t* depth, const uint8_t* board,
             int64_t size) {
  pool.depth.reserve_rows(static_cast<size_t>(size));
  pool.board.reserve_rows(static_cast<size_t>(size));
  for (int64_t i = 0; i < size; ++i) {
    *pool.depth.emplace_back() = depth[i];
    std::memcpy(pool.board.emplace_back(), board + i * n,
                static_cast<size_t>(n));
  }
}

// DFS the pool to exhaustion.
void nq_run(NqPool& pool, int n, int g, int64_t* tree, int64_t* sol) {
  std::vector<uint8_t> cur(static_cast<size_t>(n));
  while (true) {
    const int32_t* d = pool.depth.pop_back();
    if (d == nullptr) break;
    const int32_t depth = *d;
    std::memcpy(cur.data(), pool.board.pop_back(), static_cast<size_t>(n));
    *tree += nq_expand(pool, n, g, depth, cur.data(), sol);
  }
}

// ---------------------------------------------------------------------------
// PFSP
// ---------------------------------------------------------------------------

struct PfspCtx {
  int n = 0;  // jobs
  int m = 0;  // machines
  int npairs = 0;
  int lb_kind = 0;  // 0 = lb1, 1 = lb1_d, 2 = lb2
  std::vector<int32_t> ptm;        // [m][n] processing times
  std::vector<int32_t> min_heads;  // [m]
  std::vector<int32_t> min_tails;  // [m]
  std::vector<int32_t> pairs;      // [npairs][2]
  std::vector<int32_t> lags;       // [npairs][n]
  std::vector<int32_t> jsched;     // [npairs][n] job ids in Johnson order
};

struct PfspPool {
  explicit PfspPool(int n) : meta(2), prmu(static_cast<size_t>(n)) {}
  SoaDeque<int32_t> meta;  // row = [depth, limit1]
  SoaDeque<int32_t> prmu;
};

// Per-call scratch, reused across nodes.  Exported calls may run
// concurrently from different host threads (the multi-device runtime), so
// nothing lives in globals.
struct PfspScratch {
  explicit PfspScratch(const PfspCtx& c)
      : front(static_cast<size_t>(c.m)),
        child_front(static_cast<size_t>(c.m)),
        remain(static_cast<size_t>(c.m)),
        fixed(static_cast<size_t>(c.n)),
        lb_begin(static_cast<size_t>(c.n)),
        prmu(static_cast<size_t>(c.n)) {}
  std::vector<int32_t> front;        // parent head-schedule completion times
  std::vector<int32_t> child_front;  // one append step beyond the parent
  std::vector<int32_t> remain;       // per-machine unscheduled work
  std::vector<uint8_t> fixed;        // job id -> scheduled in the prefix?
  std::vector<int32_t> lb_begin;     // per-job child bounds (lb1_d)
  std::vector<int32_t> prmu;         // working copy of the node permutation
};

// Extend a head schedule by one job: the classic flowshop recurrence.
inline void pfsp_append_job(const PfspCtx& c, int32_t* front, int job) {
  const int32_t* pt = c.ptm.data();
  int32_t prev = front[0] + pt[job];
  front[0] = prev;
  for (int k = 1; k < c.m; ++k) {
    prev = (prev > front[k] ? prev : front[k]) + pt[k * c.n + job];
    front[k] = prev;
  }
}

// Parent state shared by all of its children: true (zeros-based) head
// schedule of the prefix, per-machine remaining work, prefix membership.
void pfsp_parent_state(const PfspCtx& c, const int32_t* prmu, int limit1,
                       PfspScratch& s) {
  std::memset(s.front.data(), 0, sizeof(int32_t) * c.m);
  std::memset(s.fixed.data(), 0, static_cast<size_t>(c.n));
  for (int i = 0; i <= limit1; ++i) {
    pfsp_append_job(c, s.front.data(), prmu[i]);
    s.fixed[prmu[i]] = 1;
  }
  for (int k = 0; k < c.m; ++k) {
    int32_t acc = 0;
    const int32_t* row = c.ptm.data() + static_cast<size_t>(k) * c.n;
    for (int i = limit1 + 1; i < c.n; ++i) acc += row[prmu[i]];
    s.remain[k] = acc;
  }
}

// lb1 of the child that appends `job`: one fused register pass over the
// machines — the append step's running head (`cf_k = max(cf_{k-1},
// front[k]) + pt[k][job]`), the head+remain part, and the tail chain
// (back = min_tails, since forward branching keeps limit2 == n).
// Value-identical to a full recompute. ONE copy of the recurrence:
// kStoreFront additionally materializes the child front into
// s.child_front (the staged lb2 path reuses it when the child survives
// the prefilter); the pure-lb1 hot loop skips the stores.
template <bool kStoreFront>
int32_t pfsp_lb1_child_impl(const PfspCtx& c, PfspScratch& s, int job) {
  const int32_t* pt = c.ptm.data();
  const int32_t* front = s.front.data();
  int32_t* cf_out = s.child_front.data();
  int32_t cf = front[0] + pt[job];  // child head on machine 0
  if (kStoreFront) cf_out[0] = cf;
  int32_t chain = cf + s.remain[0] - pt[job];
  int32_t lb = chain + c.min_tails[0];
  for (int k = 1; k < c.m; ++k) {
    const int32_t fk = front[k];
    cf = (cf > fk ? cf : fk) + pt[k * c.n + job];
    if (kStoreFront) cf_out[k] = cf;
    const int32_t part = cf + s.remain[k] - pt[k * c.n + job];
    if (part > chain) chain = part;
    const int32_t cand = chain + c.min_tails[k];
    if (cand > lb) lb = cand;
  }
  return lb;
}

int32_t pfsp_lb1_child(const PfspCtx& c, PfspScratch& s, int job) {
  return pfsp_lb1_child_impl<true>(c, s, job);
}

int32_t pfsp_lb1_child_fused(const PfspCtx& c, PfspScratch& s, int job) {
  return pfsp_lb1_child_impl<false>(c, s, job);
}

// lb1_d ("children bounds in one pass"): the weaker O(m)-per-child bound that
// never materializes the child schedule.  The parent front here uses the
// reference's schedule_front special case (limit1 == -1 -> min_heads), which
// only the root hits.
void pfsp_lb1d_all_children(const PfspCtx& c, const int32_t* prmu, int limit1,
                            PfspScratch& s) {
  const int32_t* front = (limit1 == -1) ? c.min_heads.data() : s.front.data();
  const int32_t* pt = c.ptm.data();
  for (int i = limit1 + 1; i < c.n; ++i) {
    const int job = prmu[i];
    int32_t lb = front[0] + s.remain[0] + c.min_tails[0];
    int32_t chain = front[0] + pt[job];
    for (int k = 1; k < c.m; ++k) {
      const int32_t head = (chain > front[k] ? chain : front[k]);
      const int32_t cand = head + s.remain[k] + c.min_tails[k];
      if (cand > lb) lb = cand;
      chain = head + pt[k * c.n + job];
    }
    s.lb_begin[job] = lb;
  }
}

// lb2 (Johnson two-machine bound) of the child that appends `job`: the
// lag-augmented Johnson schedule of the free jobs per machine pair, seeded
// with the child head schedule; early-exits once the running max already
// prunes against `incumbent` (the returned value is then still >= incumbent,
// so the caller's prune decision is unaffected).
int32_t pfsp_lb2_child(const PfspCtx& c, PfspScratch& s, int job,
                       int32_t incumbent, bool have_front = false) {
  int32_t* cf = s.child_front.data();
  if (!have_front) {  // staged caller: pfsp_lb1_child already built it
    std::memcpy(cf, s.front.data(), sizeof(int32_t) * c.m);
    pfsp_append_job(c, cf, job);
  }
  s.fixed[job] = 1;
  const int32_t* pt = c.ptm.data();
  int32_t lb = 0;
  for (int p = 0; p < c.npairs; ++p) {
    const int ma0 = c.pairs[2 * p];
    const int ma1 = c.pairs[2 * p + 1];
    const int32_t* lag = c.lags.data() + static_cast<size_t>(p) * c.n;
    const int32_t* order = c.jsched.data() + static_cast<size_t>(p) * c.n;
    const int32_t* p0 = pt + static_cast<size_t>(ma0) * c.n;
    const int32_t* p1 = pt + static_cast<size_t>(ma1) * c.n;
    int32_t t0 = cf[ma0];
    int32_t t1 = cf[ma1];
    for (int j = 0; j < c.n; ++j) {
      const int jj = order[j];
      if (s.fixed[jj]) continue;
      t0 += p0[jj];
      const int32_t ready = t0 + lag[jj];
      if (ready > t1) t1 = ready;
      t1 += p1[jj];
    }
    const int32_t a = t1 + c.min_tails[ma1];
    const int32_t b = t0 + c.min_tails[ma0];
    const int32_t pair_lb = (a > b ? a : b);
    if (pair_lb > lb) lb = pair_lb;
    if (lb > incumbent) break;
  }
  s.fixed[job] = 0;
  return lb;
}

// Expand one node: evaluate every child, fold leaves into the incumbent,
// push survivors (bound < best, strict) in ascending slot order.
int64_t pfsp_expand(const PfspCtx& c, PfspPool& pool, const int32_t* prmu,
                    int depth, int limit1, int32_t* best, int64_t* sol,
                    PfspScratch& s) {
  pfsp_parent_state(c, prmu, limit1, s);
  if (c.lb_kind == 1) pfsp_lb1d_all_children(c, prmu, limit1, s);
  const bool child_is_leaf = (depth + 1 == c.n);
  int64_t pushed = 0;
  for (int i = limit1 + 1; i < c.n; ++i) {
    const int job = prmu[i];
    int32_t lb;
    switch (c.lb_kind) {
      case 0:
        lb = pfsp_lb1_child_fused(c, s, job);
        break;
      case 1:
        lb = s.lb_begin[job];
        break;
      default:
        // Staged lb2 (the host analogue of the device tiers' staging and
        // of the reference's per-pair early exit): the O(m) incremental
        // lb1 runs first, and only survivors pay the O(P*n) Johnson pair
        // loop. Exact — lb2 >= lb1 pointwise, so an lb1-pruned child is
        // lb2-pruned too, and the returned (>= best) value makes the same
        // prune decision. Leaves skip the filter: their reported value is
        // the makespan and must come from the lb2 evaluation itself.
        if (!child_is_leaf) {
          lb = pfsp_lb1_child(c, s, job);
          if (lb >= *best) break;
          // s.child_front still holds this child's head schedule.
          lb = pfsp_lb2_child(c, s, job, *best, /*have_front=*/true);
        } else {
          lb = pfsp_lb2_child(c, s, job, *best);
        }
        break;
    }
    if (child_is_leaf) {
      ++*sol;
      if (lb < *best) *best = lb;
    } else if (lb < *best) {
      int32_t* meta = pool.meta.emplace_back();
      meta[0] = depth + 1;
      meta[1] = limit1 + 1;
      int32_t* cp = pool.prmu.emplace_back();
      std::memcpy(cp, prmu, sizeof(int32_t) * c.n);
      cp[depth] = prmu[i];
      cp[i] = prmu[depth];
      ++pushed;
    }
  }
  return pushed;
}

void pfsp_seed(PfspPool& pool, int n, const int32_t* depth,
               const int32_t* limit1, const int32_t* prmu, int64_t size) {
  pool.meta.reserve_rows(static_cast<size_t>(size));
  pool.prmu.reserve_rows(static_cast<size_t>(size));
  for (int64_t i = 0; i < size; ++i) {
    int32_t* meta = pool.meta.emplace_back();
    meta[0] = depth[i];
    meta[1] = limit1[i];
    std::memcpy(pool.prmu.emplace_back(), prmu + i * n, sizeof(int32_t) * n);
  }
}

// DFS the pool to exhaustion.
void pfsp_run(const PfspCtx& c, PfspPool& pool, int32_t* best, int64_t* tree,
              int64_t* sol, PfspScratch& s) {
  while (true) {
    const int32_t* meta = pool.meta.pop_back();
    if (meta == nullptr) break;
    const int32_t depth = meta[0];
    const int32_t limit1 = meta[1];
    std::memcpy(s.prmu.data(), pool.prmu.pop_back(), sizeof(int32_t) * c.n);
    *tree += pfsp_expand(c, pool, s.prmu.data(), depth, limit1, best, sol, s);
  }
}

}  // namespace

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

extern "C" {

// ---- N-Queens -------------------------------------------------------------

// Full DFS from the root (the sequential tier in one call).
void tts_nq_sequential(int32_t n, int32_t g, int64_t* tree, int64_t* sol) {
  NqPool pool(n);
  *pool.depth.emplace_back() = 0;
  uint8_t* root = pool.board.emplace_back();
  for (int i = 0; i < n; ++i) root[i] = static_cast<uint8_t>(i);
  *tree = 0;
  *sol = 0;
  nq_run(pool, n, g, tree, sol);
}

// BFS (pop-front) expansion until the frontier holds >= target nodes or goes
// empty.  The frontier enters and leaves through the caller's SoA buffers,
// whose capacity must be >= max(size_in, target + n - 1).  Returns the new
// frontier size; *tree / *sol receive the phase increments.
int64_t tts_nq_warmup(int32_t n, int32_t g, int64_t target, int32_t* depth,
                      uint8_t* board, int64_t size_in, int64_t* tree,
                      int64_t* sol) {
  NqPool pool(n);
  nq_seed(pool, n, depth, board, size_in);
  *tree = 0;
  *sol = 0;
  std::vector<uint8_t> cur(static_cast<size_t>(n));
  while (pool.depth.size() > 0 &&
         pool.depth.size() < static_cast<size_t>(target)) {
    const int32_t d = *pool.depth.pop_front();
    std::memcpy(cur.data(), pool.board.pop_front(), static_cast<size_t>(n));
    *tree += nq_expand(pool, n, g, d, cur.data(), sol);
  }
  const int64_t out = static_cast<int64_t>(pool.depth.size());
  for (int64_t i = 0; i < out; ++i) {
    depth[i] = *pool.depth.row(i);
    std::memcpy(board + i * n, pool.board.row(i), static_cast<size_t>(n));
  }
  return out;
}

// DFS a whole frontier batch to completion (the drain phase).
void tts_nq_drain(int32_t n, int32_t g, const int32_t* depth,
                  const uint8_t* board, int64_t size, int64_t* tree,
                  int64_t* sol) {
  NqPool pool(n);
  nq_seed(pool, n, depth, board, size);
  *tree = 0;
  *sol = 0;
  nq_run(pool, n, g, tree, sol);
}

// Consume device safety labels for a chunk of parents: emit surviving
// children into the caller's buffers (capacity count * n rows) in
// (parent, slot) ascending order.  Returns the child count; *sol_inc counts
// depth==N parents.
int64_t tts_nq_generate(int32_t n, const int32_t* pdepth,
                        const uint8_t* pboard, int64_t count,
                        const uint8_t* labels, int32_t* cdepth,
                        uint8_t* cboard, int64_t* sol_inc) {
  int64_t out = 0;
  *sol_inc = 0;
  for (int64_t i = 0; i < count; ++i) {
    const int32_t depth = pdepth[i];
    if (depth == n) {
      ++*sol_inc;
      continue;
    }
    const uint8_t* board = pboard + i * n;
    const uint8_t* lab = labels + i * n;
    for (int j = depth; j < n; ++j) {
      if (!lab[j]) continue;
      cdepth[out] = depth + 1;
      uint8_t* child = cboard + out * n;
      std::memcpy(child, board, static_cast<size_t>(n));
      child[depth] = board[j];
      child[j] = board[depth];
      ++out;
    }
  }
  return out;
}

// ---- PFSP -----------------------------------------------------------------

void* tts_pfsp_new(int32_t jobs, int32_t machines, int32_t lb_kind,
                   const int32_t* ptm, const int32_t* min_heads,
                   const int32_t* min_tails, int32_t npairs,
                   const int32_t* pairs, const int32_t* lags,
                   const int32_t* jsched) {
  auto* c = new PfspCtx();
  c->n = jobs;
  c->m = machines;
  c->npairs = npairs;
  c->lb_kind = lb_kind;
  c->ptm.assign(ptm, ptm + static_cast<size_t>(machines) * jobs);
  c->min_heads.assign(min_heads, min_heads + machines);
  c->min_tails.assign(min_tails, min_tails + machines);
  if (npairs > 0) {
    c->pairs.assign(pairs, pairs + static_cast<size_t>(npairs) * 2);
    c->lags.assign(lags, lags + static_cast<size_t>(npairs) * jobs);
    c->jsched.assign(jsched, jsched + static_cast<size_t>(npairs) * jobs);
  }
  return c;
}

void tts_pfsp_free(void* ctx) { delete static_cast<PfspCtx*>(ctx); }

// Full B&B DFS from the root (the sequential tier in one call).
void tts_pfsp_sequential(void* ctx, int32_t best_in, int64_t* tree,
                         int64_t* sol, int32_t* best_out) {
  const PfspCtx& c = *static_cast<PfspCtx*>(ctx);
  PfspPool pool(c.n);
  int32_t* meta = pool.meta.emplace_back();
  meta[0] = 0;
  meta[1] = -1;
  int32_t* prmu = pool.prmu.emplace_back();
  for (int i = 0; i < c.n; ++i) prmu[i] = i;
  PfspScratch s(c);
  int32_t best = best_in;
  *tree = 0;
  *sol = 0;
  pfsp_run(c, pool, &best, tree, sol, s);
  *best_out = best;
}

// BFS warm-up; same contract as tts_nq_warmup (buffer capacity
// >= max(size_in, target + n - 1)); *best_io carries the incumbent.
int64_t tts_pfsp_warmup(void* ctx, int64_t target, int32_t* depth,
                        int32_t* limit1, int32_t* prmu, int64_t size_in,
                        int64_t* tree, int64_t* sol, int32_t* best_io) {
  const PfspCtx& c = *static_cast<PfspCtx*>(ctx);
  PfspPool pool(c.n);
  pfsp_seed(pool, c.n, depth, limit1, prmu, size_in);
  PfspScratch s(c);
  int32_t best = *best_io;
  *tree = 0;
  *sol = 0;
  while (pool.meta.size() > 0 &&
         pool.meta.size() < static_cast<size_t>(target)) {
    const int32_t* meta = pool.meta.pop_front();
    const int32_t d = meta[0];
    const int32_t l1 = meta[1];
    std::memcpy(s.prmu.data(), pool.prmu.pop_front(), sizeof(int32_t) * c.n);
    *tree += pfsp_expand(c, pool, s.prmu.data(), d, l1, &best, sol, s);
  }
  const int64_t out = static_cast<int64_t>(pool.meta.size());
  for (int64_t i = 0; i < out; ++i) {
    const int32_t* meta = pool.meta.row(i);
    depth[i] = meta[0];
    limit1[i] = meta[1];
    std::memcpy(prmu + i * c.n, pool.prmu.row(i), sizeof(int32_t) * c.n);
  }
  *best_io = best;
  return out;
}

// DFS a whole frontier batch to completion (the drain phase).
void tts_pfsp_drain(void* ctx, const int32_t* depth, const int32_t* limit1,
                    const int32_t* prmu, int64_t size, int64_t* tree,
                    int64_t* sol, int32_t* best_io) {
  const PfspCtx& c = *static_cast<PfspCtx*>(ctx);
  PfspPool pool(c.n);
  pfsp_seed(pool, c.n, depth, limit1, prmu, size);
  PfspScratch s(c);
  int32_t best = *best_io;
  *tree = 0;
  *sol = 0;
  pfsp_run(c, pool, &best, tree, sol, s);
  *best_io = best;
}

// Consume device bounds for a chunk of parents: leaves fold into the
// incumbent first (whole chunk), then survivors (bound < folded best) are
// emitted in (parent, slot) ascending order into the caller's buffers
// (capacity count * n rows).  Mirrors PFSPProblem.generate_children.
int64_t tts_pfsp_generate(void* ctx, const int32_t* pdepth,
                          const int32_t* plimit1, const int32_t* pprmu,
                          int64_t count, const int32_t* bounds,
                          int32_t* cdepth, int32_t* climit1, int32_t* cprmu,
                          int64_t* sol_inc, int32_t* best_io) {
  const PfspCtx& c = *static_cast<PfspCtx*>(ctx);
  const int n = c.n;
  int32_t best = *best_io;
  *sol_inc = 0;
  // Pass 1: leaf slots update the incumbent before any pruning decision.
  for (int64_t i = 0; i < count; ++i) {
    if (pdepth[i] + 1 != n) continue;
    const int32_t* b = bounds + i * n;
    for (int j = plimit1[i] + 1; j < n; ++j) {
      ++*sol_inc;
      if (b[j] < best) best = b[j];
    }
  }
  // Pass 2: non-leaf survivors.
  int64_t out = 0;
  for (int64_t i = 0; i < count; ++i) {
    const int32_t depth = pdepth[i];
    if (depth + 1 == n) continue;
    const int32_t l1 = plimit1[i];
    const int32_t* prmu = pprmu + i * n;
    const int32_t* b = bounds + i * n;
    for (int j = l1 + 1; j < n; ++j) {
      if (b[j] >= best) continue;
      cdepth[out] = depth + 1;
      climit1[out] = l1 + 1;
      int32_t* cp = cprmu + out * n;
      std::memcpy(cp, prmu, sizeof(int32_t) * n);
      cp[depth] = prmu[j];
      cp[j] = prmu[depth];
      ++out;
    }
  }
  *best_io = best;
  return out;
}

}  // extern "C"
